"""S3-compatible HTTP gateway over the FileSystem SDK.

Mirrors the reference's MinIO-based gateway semantics (pkg/gateway):
  - buckets = top-level directories of the volume (gateway.go jfsObjects)
  - objects = files; "dir/" keys list by prefix via the namespace itself
  - multipart uploads assemble under /.sys/multipart (gateway.go:188-196)
  - ETag = hex JTH-256 prefix stored in an xattr (etag-in-xattr like the
    reference's s3-etag xattr)

Serving-plane data paths (ISSUE 15, gateway/serve.py):
  - GET streams block-sized spans through the vfs streaming reader (the
    PR 10 readahead window ramps for sequential S3 consumers) with
    bounded gateway-side buffering and chunked socket writes;
  - PUT / UploadPart stream the request body into the vfs writer in
    block-sized pieces, so the bytes ride the ingest/dedup/compress
    plane (PR 5/8) exactly like FUSE writes;
  - CompleteMultipartUpload and CopyObject stitch server-side at the
    slice/metadata level (``fs.copy_range`` -> meta copy_file_range
    slice increfs) — no part is ever re-read or re-written;
  - ListObjectsV2 pages through an ordered incremental readdir walk
    (serve.OrderedKeyWalker): real continuation tokens, bounded memory
    at any max-keys, no full-bucket recursion;
  - every request passes the bounded admission gate (overload sheds as
    503 SlowDown) and runs under the tenant scope of its access key —
    SigV4 verification maps multiple access keys to tenants.
"""

from __future__ import annotations

import errno as _errno
import posixpath
import re
import urllib.parse
import uuid
from xml.sax.saxutils import escape

from ..meta.types import TYPE_DIRECTORY
from .. import native
from ..tpu.jth256 import digest_hex
from ..utils import get_logger
from ..fs import FSError, FileSystem
from . import BaseHandler, HTTPAdapter
from .serve import (
    UNSATISFIABLE,
    GatewayAuth,
    OrderedKeyWalker,
    ServingPlane,
    parse_range,
)

logger = get_logger("gateway.s3")

SYS_MULTIPART = "/.sys/multipart"
ETAG_XATTR = b"s3.etag"
NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _etag(data: bytes) -> str:
    return digest_hex(native.jth256(data))[:32]


class S3Gateway(HTTPAdapter):
    _name = "s3-gateway"

    def __init__(
        self,
        fs: FileSystem,
        address: str = "127.0.0.1",
        port: int = 9000,
        access_key: str = "",
        secret_key: str = "",
        tenant_keys: dict[str, str] | None = None,
        max_inflight: int = 64,
    ):
        super().__init__(address, port)
        self.fs = fs
        auth = GatewayAuth()
        if access_key:
            auth.add_key(access_key, secret_key)
        for ak, sk in (tenant_keys or {}).items():
            auth.add_key(ak, sk)
        self.plane = ServingPlane(fs.vfs, auth, max_inflight=max_inflight)
        # trusted-boundary mode serves through the CALLER's FileSystem
        # context (its uid), not a synthetic tenant
        self.plane.bind_anonymous(fs)
        gw = self

        class Handler(BaseHandler):
            def log_message(self, fmt, *args):
                logger.debug(fmt, *args)

            def _authorized(self):
                """Verify AWS SigV4 when the gateway has credentials and
                map the access key to its tenant (reference: MinIO auth
                layer in pkg/gateway).  Signature, date window (replay
                bound) and the streaming-scheme rejection happen here;
                PAYLOAD hashes are verified on the data path while the
                body streams (serve.stream_body_in), never by buffering.
                Returns the Tenant, or None (error already sent)."""
                if not gw.plane.auth.enabled:
                    return gw.plane.tenant("")
                import datetime as _dt

                headers = {k.lower(): v for k, v in self.headers.items()}
                amz_date = headers.get("x-amz-date", "")
                try:
                    ts = _dt.datetime.strptime(
                        amz_date, "%Y%m%dT%H%M%SZ"
                    ).replace(tzinfo=_dt.timezone.utc)
                except ValueError:
                    self._drain()
                    gw.plane.note_auth_failure()
                    self._error(403, "AccessDenied", "missing x-amz-date")
                    return None
                skew = abs(
                    (_dt.datetime.now(_dt.timezone.utc) - ts).total_seconds()
                )
                if skew > 900:
                    self._drain()
                    gw.plane.note_auth_failure()
                    self._error(403, "RequestTimeTooSkewed")
                    return None
                if headers.get("x-amz-content-sha256", "").startswith(
                        "STREAMING-"):
                    self._drain()
                    self._error(
                        501, "NotImplemented",
                        "streaming chunked payloads are not supported",
                    )
                    return None
                u = urllib.parse.urlsplit(self.path)
                query = {
                    k: v[0]
                    for k, v in urllib.parse.parse_qs(
                        u.query, keep_blank_values=True
                    ).items()
                }
                ak = gw.plane.auth.verify(
                    self.command,
                    urllib.parse.unquote(u.path),
                    query,
                    headers,
                    self.headers.get("Authorization", ""),
                )
                if ak is None:
                    self._drain()
                    gw.plane.note_auth_failure()
                    self._error(403, "SignatureDoesNotMatch")
                    return None
                return gw.plane.tenant(ak)

            def _declared_sha(self) -> str | None:
                """The signed payload hash a streamed body must match
                (None = unsigned / trusted mode: nothing to check)."""
                if not gw.plane.auth.enabled:
                    return None
                sha = self.headers.get("x-amz-content-sha256", "")
                if not sha or sha == "UNSIGNED-PAYLOAD":
                    return None
                return sha

            def _verify_buffered(self, body: bytes) -> bool:
                """Payload-hash check for the small, buffered control
                bodies (CompleteMultipartUpload manifest)."""
                want = self._declared_sha()
                if want is None:
                    return True
                import hashlib as _hashlib

                if _hashlib.sha256(body).hexdigest() == want:
                    return True
                self._error(400, "XAmzContentSHA256Mismatch")
                return False

            def _params(self):
                u = urllib.parse.urlsplit(self.path)
                q = urllib.parse.parse_qs(u.query, keep_blank_values=True)
                parts = u.path.lstrip("/").split("/", 1)
                bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                return bucket, key, q

            def _xml(self, code: int, body: str):
                data = ('<?xml version="1.0" encoding="UTF-8"?>' + body).encode()
                gw.plane.note_error(code)
                self.send_response(code)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, s3code: str, msg: str = ""):
                self._xml(code, f"<Error><Code>{s3code}</Code>"
                                f"<Message>{escape(msg or s3code)}</Message></Error>")

            def _shed(self):
                """Admission-gate refusal: S3's retryable overload reply.
                The unread body is NOT drained (that would spend the very
                bandwidth shedding exists to protect) — close instead."""
                self.close_connection = True
                gw.plane.note_error(503)
                body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                        b"<Error><Code>SlowDown</Code>"
                        b"<Message>Reduce your request rate.</Message>"
                        b"</Error>")
                self.send_response(503)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            # -- dispatch --------------------------------------------------
            def do_GET(self):
                t = self._authorized()
                if t is None:
                    return
                bucket, key, q = self._params()
                op = "list" if not key else "get"
                with gw.plane.admitted(op, t) as adm:
                    if adm is None:
                        return self._shed()
                    try:
                        if not bucket:
                            return gw._list_buckets(self, t)
                        if not key:
                            return gw._list_objects(self, t, bucket, q)
                        return gw._get_object(self, t, bucket, key)
                    except ValueError:
                        self._error(400, "InvalidArgument")
                    except FSError as e:
                        self._map_fs_error(e)
                    except OSError as e:
                        # storage-layer failure (outage, breaker open)
                        # surfacing before the headers committed: a
                        # clean, retryable 500 — never a dead socket
                        logger.warning("GET failed: %s", e)
                        self._error(500, "InternalError", str(e))

            def do_HEAD(self):
                t = self._authorized()
                if t is None:
                    return
                bucket, key, q = self._params()
                with gw.plane.admitted("head", t) as adm:
                    if adm is None:
                        return self._shed()
                    try:
                        if bucket and not key:
                            t.fs.stat("/" + bucket)
                            return self._empty(200)
                        return gw._head_object(self, t, bucket, key)
                    except FSError as e:
                        gw.plane.note_error(
                            404 if e.errno == _errno.ENOENT else 500)
                        self._empty(
                            404 if e.errno == _errno.ENOENT else 500)

            def do_PUT(self):
                t = self._authorized()
                if t is None:
                    return
                bucket, key, q = self._params()
                op = "part" if "partNumber" in q else "put"
                with gw.plane.admitted(op, t) as adm:
                    if adm is None:
                        return self._shed()
                    try:
                        if bucket and not key:
                            return gw._create_bucket(self, t, bucket)
                        if "partNumber" in q and "uploadId" in q:
                            return gw._upload_part(
                                self, t, bucket, key, q["uploadId"][0],
                                int(q["partNumber"][0]),
                            )
                        return gw._put_object(self, t, bucket, key)
                    except ValueError:
                        self._drain()
                        self._error(400, "InvalidArgument")
                    except FSError as e:
                        self._drain()
                        self._map_fs_error(e)
                    except OSError as e:
                        logger.warning("PUT failed: %s", e)
                        self._drain()
                        self._error(500, "InternalError", str(e))

            def do_POST(self):
                t = self._authorized()
                if t is None:
                    return
                bucket, key, q = self._params()
                with gw.plane.admitted("multipart", t) as adm:
                    if adm is None:
                        return self._shed()
                    try:
                        if "uploads" in q:
                            return gw._create_multipart(self, t, bucket, key)
                        if "uploadId" in q:
                            return gw._complete_multipart(
                                self, t, bucket, key, q["uploadId"][0])
                        self._drain()
                        self._error(400, "InvalidRequest")
                    except ValueError:
                        self._error(400, "InvalidArgument")
                    except FSError as e:
                        self._map_fs_error(e)
                    except OSError as e:
                        logger.warning("POST failed: %s", e)
                        self._error(500, "InternalError", str(e))

            def do_DELETE(self):
                t = self._authorized()
                if t is None:
                    return
                bucket, key, q = self._params()
                with gw.plane.admitted("delete", t) as adm:
                    if adm is None:
                        return self._shed()
                    try:
                        if "uploadId" in q:
                            return gw._abort_multipart(
                                self, t, bucket, key, q["uploadId"][0])
                        if bucket and not key:
                            return gw._delete_bucket(self, t, bucket)
                        return gw._delete_object(self, t, bucket, key)
                    except ValueError:
                        self._error(400, "InvalidArgument")
                    except FSError as e:
                        self._map_fs_error(e)
                    except OSError as e:
                        logger.warning("DELETE failed: %s", e)
                        self._error(500, "InternalError", str(e))

            def _map_fs_error(self, e: FSError):
                if e.errno == _errno.ENOENT:
                    self._error(404, "NoSuchKey", str(e))
                elif e.errno == _errno.ENOTEMPTY:
                    self._error(409, "BucketNotEmpty", str(e))
                elif e.errno in (_errno.EACCES, _errno.EPERM):
                    self._error(403, "AccessDenied", str(e))
                else:
                    self._error(500, "InternalError", str(e))

        self._handler_cls = Handler

    # -- bucket ops --------------------------------------------------------

    def _list_buckets(self, h, t):
        entries = t.fs.listdir("/", want_attr=True)
        items = "".join(
            f"<Bucket><Name>{escape(e.name.decode())}</Name>"
            f"<CreationDate>1970-01-01T00:00:00.000Z</CreationDate></Bucket>"
            for e in entries
            if e.attr and e.attr.typ == TYPE_DIRECTORY and not e.name.startswith(b".")
        )
        h._xml(200, f'<ListAllMyBucketsResult xmlns="{NS}">'
                    f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>")

    def _create_bucket(self, h, t, bucket: str):
        h._drain()  # CreateBucketConfiguration XML is accepted-as-given
        try:
            t.fs.mkdir("/" + bucket, 0o777)
        except FSError as e:
            if e.errno != _errno.EEXIST:
                raise
        h._empty(200, {"Location": "/" + bucket})

    def _delete_bucket(self, h, t, bucket: str):
        t.fs.rmdir("/" + bucket)
        h._empty(204)

    # -- object ops --------------------------------------------------------

    def _obj_path(self, bucket: str, key: str) -> str:
        p = posixpath.normpath(f"/{bucket}/{key}")
        if not p.startswith(f"/{bucket}/"):
            raise FSError(_errno.EPERM, key)  # path escape attempt
        return p

    def _put_object(self, h, t, bucket: str, key: str):
        fs = t.fs
        fs.stat("/" + bucket)
        length = int(h.headers.get("Content-Length", 0) or 0)
        path = self._obj_path(bucket, key)
        if key.endswith("/"):
            if length:
                h._drain()
                raise FSError(_errno.EINVAL, key)
            fs.makedirs(path)
            return h._empty(200, {"ETag": '"d41d8cd98f00b204e9800998ecf8427e"'})
        copy_src = h.headers.get("x-amz-copy-source")
        # the destination's parent chain is created only once the
        # request is known-good (just before the publishing rename): a
        # failed copy-source or truncated body must not leave empty
        # directory trees that make DeleteBucket fail BucketNotEmpty on
        # a bucket ListObjects shows as empty
        parent = posixpath.dirname(path)
        if copy_src:
            h._drain()  # a copy request's body is ignored, not left on
            # the socket to desync the next keep-alive request
            src = urllib.parse.unquote(copy_src.lstrip("/"))
            sbucket, _, skey = src.partition("/")
            # Same escape guard as destination keys (no ../ traversal);
            # the copy itself is a server-side slice share — no data
            # bytes move through the gateway
            spath = self._obj_path(sbucket, skey)
            sattr = fs.stat(spath)
            try:
                et = fs.getxattr(spath, ETAG_XATTR).decode()
            except FSError:
                et = f"{sattr.length:x}-{sattr.mtime:x}"
            if spath != path:
                # copy-to-SELF is a metadata refresh in S3; otherwise
                # the slice share lands in a temp key and publishes by
                # rename, so a mid-copy failure never leaves the live
                # destination truncated or partial
                fs.makedirs(self._TMP_DIR)
                tmp = f"{self._TMP_DIR}/{uuid.uuid4().hex}"
                with fs.create(tmp):
                    pass
                try:
                    fs.copy_range(spath, tmp)
                except FSError:
                    self._discard(fs, tmp)
                    raise
                try:
                    fs.setxattr(tmp, ETAG_XATTR, et.encode())
                except FSError:
                    pass  # etag falls back to length-mtime on read
                if parent != "/":
                    fs.makedirs(parent)
                fs.rename(tmp, path)
            return h._xml(200, f'<CopyObjectResult xmlns="{NS}">'
                               f"<ETag>&quot;{et}&quot;</ETag></CopyObjectResult>")
        # data path: the body streams into the vfs writer in block-sized
        # pieces (ingest/dedup/compress engage), never one RAM buffer.
        # It lands in a TEMP key first: an overwrite PUT whose body dies
        # or lies about its hash must never have touched the live
        # destination object (one atomic rename publishes it)
        tmp, et, got, sha_ok = self._stream_to_temp(h, fs, length)
        if got < length:
            # client truncated the body: the socket is desynced — drop
            # the partial temp and the connection
            self._discard(fs, tmp)
            h.close_connection = True
            return h._error(400, "IncompleteBody")
        if not sha_ok:
            self._discard(fs, tmp)
            return h._error(400, "XAmzContentSHA256Mismatch")
        try:
            fs.setxattr(tmp, ETAG_XATTR, et.encode())
        except FSError:
            pass
        if parent != "/":
            fs.makedirs(parent)
        fs.rename(tmp, path)
        h._empty(200, {"ETag": f'"{et}"'})

    _TMP_DIR = "/.sys/tmp"

    def _stream_to_temp(self, h, fs, length: int):
        """Stream the request body into a fresh temp file under the
        /.sys staging area; the caller publishes it with one rename
        once the bytes are complete and hash-verified.  Returns
        (tmp_path, etag, bytes_read, sha_ok).  A vfs failure
        MID-STREAM (ENOSPC, breaker open) discards the temp before
        propagating — the caller never learns the path, so nobody else
        could clean it up."""
        fs.makedirs(self._TMP_DIR)
        tmp = f"{self._TMP_DIR}/{uuid.uuid4().hex}"
        try:
            with fs.create(tmp) as f:
                et, got, sha_ok = self.plane.stream_in(
                    h, f, length, want_sha=h._declared_sha()
                )
        except OSError:
            self._discard(fs, tmp)
            raise
        return tmp, et, got, sha_ok

    @staticmethod
    def _discard(fs, path: str) -> None:
        try:
            fs.unlink(path)
        except FSError:
            pass  # unwind of a failed PUT: the object may never have landed

    def _get_object(self, h, t, bucket: str, key: str):
        fs = t.fs
        path = self._obj_path(bucket, key)
        attr = fs.stat(path)
        if attr.typ == TYPE_DIRECTORY:
            raise FSError(_errno.ENOENT, key)
        rng = parse_range(h.headers.get("Range"), attr.length)
        if rng is UNSATISFIABLE:
            gw_code = 416
            self.plane.note_error(gw_code)
            h.send_response(gw_code)
            h.send_header("Content-Range", f"bytes */{attr.length}")
            h.send_header("Content-Length", "0")
            h.end_headers()
            return
        if rng is None:
            start, end, code = 0, attr.length - 1, 200
        else:
            (start, end), code = rng, 206
        length = end - start + 1 if attr.length else 0
        etag = self._etag_of(fs, path, attr)
        if not length:
            h.send_response(code)
            h.send_header("Content-Type", "application/octet-stream")
            h.send_header("Content-Length", "0")
            h.send_header("Last-Modified", _http_date(attr.mtime))
            h.send_header("ETag", f'"{etag}"')
            h.end_headers()
            return
        # stream block-sized spans through the vfs reader: sequential
        # spans ramp the PR 10 readahead window; at most ONE span is
        # buffered gateway-side at any instant.  The FIRST
        # span is read BEFORE the headers commit, so a failing read
        # (cold miss during an outage) still maps to a clean 500 —
        # only a mid-stream failure degrades to a closed connection
        with fs.open(path) as f:
            first = f.pread(start, min(self.plane.span, length))
            h.send_response(code)
            h.send_header("Content-Type", "application/octet-stream")
            h.send_header("Content-Length", str(length))
            h.send_header("Last-Modified", _http_date(attr.mtime))
            h.send_header("ETag", f'"{etag}"')
            if code == 206:
                h.send_header("Content-Range",
                              f"bytes {start}-{end}/{attr.length}")
            h.end_headers()
            sent = 0
            try:
                sent = self.plane.write_span(h.wfile, first)
                if sent < length:
                    sent += self.plane.stream_out(
                        h.wfile, f, start + sent, length - sent)
            except OSError:
                # headers are committed: a socket or backend failure
                # here can only be signalled by closing — sending an
                # error response would inject bytes into the body
                pass
        if sent < length:
            # the file shrank (or the backend died) mid-stream: the
            # promised Content-Length cannot be met — kill the
            # keep-alive so the client sees a truncation, not a hung
            # read or a phantom second response
            h.close_connection = True

    def _head_object(self, h, t, bucket: str, key: str):
        fs = t.fs
        path = self._obj_path(bucket, key)
        attr = fs.stat(path)
        if attr.typ == TYPE_DIRECTORY and not key.endswith("/"):
            raise FSError(_errno.ENOENT, key)
        h._empty(200, {
            "Content-Length": str(attr.length),
            "Content-Type": "application/octet-stream",
            "Last-Modified": _http_date(attr.mtime),
            "ETag": f'"{self._etag_of(fs, path, attr)}"',
        })

    def _delete_object(self, h, t, bucket: str, key: str):
        fs = t.fs
        path = self._obj_path(bucket, key)
        try:
            attr = fs.stat(path)
            if attr.typ == TYPE_DIRECTORY:
                fs.rmdir(path)
            else:
                fs.unlink(path)
        except FSError as e:
            if e.errno != _errno.ENOENT:  # S3 delete is idempotent
                raise
        h._empty(204)

    def _etag_of(self, fs, path: str, attr) -> str:
        try:
            return fs.getxattr(path, ETAG_XATTR).decode()
        except FSError:
            return f"{attr.length:x}-{attr.mtime:x}"

    # -- listing -----------------------------------------------------------

    def _list_objects(self, h, t, bucket: str, q):
        """ListObjectsV2 with real pagination (ISSUE 15): keys stream in
        sort order from the incremental walker; the page stops at
        max-keys and the NextContinuationToken is the last item emitted
        (a key, or a CommonPrefixes entry whose whole subtree is then
        skipped on resume).  Memory is bounded by the page + the walk
        stack — never the bucket."""
        fs = t.fs
        fs.stat("/" + bucket)
        prefix = q.get("prefix", [""])[0]
        delimiter = q.get("delimiter", [""])[0]
        max_keys = int(q.get("max-keys", ["1000"])[0])
        token = q.get(
            "continuation-token", q.get("start-after", q.get("marker", [""]))
        )[0]

        walker = OrderedKeyWalker(fs, bucket, prefix, after=token)
        if delimiter and token.endswith(delimiter):
            # resuming after a rolled-up CommonPrefixes entry: everything
            # under it was already represented by that one entry
            walker.skip = token
        contents: list[tuple[str, object]] = []
        prefixes: list[str] = []
        truncated, next_token = False, ""
        if max_keys > 0:
            last = ""
            for key, attr in walker:
                item_is_prefix = False
                if delimiter:
                    rest = key[len(prefix):]
                    cut = rest.find(delimiter)
                    if cut >= 0:
                        item_is_prefix = True
                        pfx = prefix + rest[: cut + 1]
                if len(contents) + len(prefixes) >= max_keys:
                    # this item proves more remain: the page is full
                    truncated, next_token = True, last
                    break
                if item_is_prefix:
                    prefixes.append(pfx)
                    last = pfx
                    # skip the rest of the rolled-up subtree: the walker
                    # discards (and for '/' delimiters prunes) below it
                    walker.skip = pfx
                else:
                    contents.append((key, attr))
                    last = key

        body = "".join(
            f"<Contents><Key>{escape(k)}</Key>"
            f"<LastModified>{_iso_date(a.mtime)}</LastModified>"
            f"<Size>{a.length}</Size>"
            f"<StorageClass>STANDARD</StorageClass></Contents>"
            for k, a in contents
        ) + "".join(
            f"<CommonPrefixes><Prefix>{escape(p)}</Prefix></CommonPrefixes>"
            for p in prefixes
        )
        h._xml(200, f'<ListBucketResult xmlns="{NS}">'
                    f"<Name>{escape(bucket)}</Name><Prefix>{escape(prefix)}</Prefix>"
                    f"<KeyCount>{len(contents) + len(prefixes)}</KeyCount>"
                    f"<MaxKeys>{max_keys}</MaxKeys>"
                    f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
                    + (f"<NextContinuationToken>{escape(next_token)}</NextContinuationToken>"
                       if truncated else "")
                    + body + "</ListBucketResult>")

    # -- multipart ---------------------------------------------------------

    _UPLOAD_ID_RE = re.compile(r"^[0-9a-f]{32}$")

    def _mp_dir(self, upload_id: str) -> str:
        # uploadId is attacker-controlled: only the exact uuid4-hex shape
        # generated by _create_multipart may reach the path join, else
        # '../' ids escape /.sys/multipart (bypassing the _obj_path guard)
        if not self._UPLOAD_ID_RE.fullmatch(upload_id):
            raise ValueError("invalid upload id")
        return f"{SYS_MULTIPART}/{upload_id}"

    def _check_upload_id(self, h, upload_id: str) -> bool:
        if not self._UPLOAD_ID_RE.fullmatch(upload_id):
            h._drain()  # an unread body desyncs the keep-alive stream
            h._error(404, "NoSuchUpload", "invalid upload id")
            return False
        return True

    def _create_multipart(self, h, t, bucket: str, key: str):
        fs = t.fs
        fs.stat("/" + bucket)
        h._drain()
        upload_id = uuid.uuid4().hex
        fs.makedirs(self._mp_dir(upload_id))
        fs.write_file(f"{self._mp_dir(upload_id)}/.key",
                      f"{bucket}/{key}".encode())
        h._xml(200, f'<InitiateMultipartUploadResult xmlns="{NS}">'
                    f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
                    f"<UploadId>{upload_id}</UploadId>"
                    f"</InitiateMultipartUploadResult>")

    def _upload_part(self, h, t, bucket: str, key: str, upload_id: str, num: int):
        if not self._check_upload_id(h, upload_id):
            return
        fs = t.fs
        length = int(h.headers.get("Content-Length", 0) or 0)
        part = f"{self._mp_dir(upload_id)}/{num:05d}"
        # part bodies stream through the same ingest/dedup write path as
        # plain PUTs — duplicate part content elides its backend PUTs —
        # and land via the same temp+rename: a failed retry of a part
        # must not destroy the earlier good upload of that part number
        tmp, et, got, sha_ok = self._stream_to_temp(h, fs, length)
        if got < length:
            self._discard(fs, tmp)
            h.close_connection = True
            return h._error(400, "IncompleteBody")
        if not sha_ok:
            self._discard(fs, tmp)
            return h._error(400, "XAmzContentSHA256Mismatch")
        try:
            fs.setxattr(tmp, ETAG_XATTR, et.encode())
        except FSError:
            pass
        fs.rename(tmp, part)
        h._empty(200, {"ETag": f'"{et}"'})

    def _complete_multipart(self, h, t, bucket: str, key: str, upload_id: str):
        if not self._check_upload_id(h, upload_id):
            return
        fs = t.fs
        body = h._body()  # part manifest (small control payload)
        if not h._verify_buffered(body):
            return
        mp = self._mp_dir(upload_id)
        names = sorted(
            e.name.decode() for e in fs.listdir(mp) if e.name != b".key"
        )
        path = self._obj_path(bucket, key)
        # server-side stitch (ISSUE 15): each part's slices are SHARED
        # into a TEMP key at its offset (meta copy_file_range increfs) —
        # zero object-store reads or writes happen here — and the
        # finished object publishes with one rename: a mid-stitch
        # failure (or a GET racing the loop) never sees the live
        # destination truncated or partial.  Parts are deleted only
        # AFTER the rename lands (decref leaves the shared data alone).
        fs.makedirs(self._TMP_DIR)
        tmp = f"{self._TMP_DIR}/{uuid.uuid4().hex}"
        with fs.create(tmp):
            pass
        etags, off = [], 0
        try:
            for n in names:
                ppath = f"{mp}/{n}"
                pattr = fs.stat(ppath)
                try:
                    etags.append(fs.getxattr(ppath, ETAG_XATTR).decode())
                except FSError:
                    etags.append(f"{pattr.length:x}")
                if pattr.length:
                    fs.copy_range(ppath, tmp, off_out=off)
                off += pattr.length
        except FSError:
            self._discard(fs, tmp)
            raise
        et = _etag("".join(etags).encode()) + f"-{len(names)}"
        try:
            fs.setxattr(tmp, ETAG_XATTR, et.encode())
        except FSError:
            pass
        parent = posixpath.dirname(path)
        if parent != "/":
            fs.makedirs(parent)
        fs.rename(tmp, path)
        fs.remove_all(mp)
        h._xml(200, f'<CompleteMultipartUploadResult xmlns="{NS}">'
                    f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
                    f"<ETag>&quot;{et}&quot;</ETag>"
                    f"</CompleteMultipartUploadResult>")

    def _abort_multipart(self, h, t, bucket: str, key: str, upload_id: str):
        if not self._check_upload_id(h, upload_id):
            return
        try:
            t.fs.remove_all(self._mp_dir(upload_id))
        except FSError:
            pass
        h._empty(204)


def _http_date(ts: int) -> str:
    import email.utils

    return email.utils.formatdate(ts, usegmt=True)


def _iso_date(ts: int) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.000Z")
