"""S3-compatible HTTP gateway over the FileSystem SDK.

Mirrors the reference's MinIO-based gateway semantics (pkg/gateway):
  - buckets = top-level directories of the volume (gateway.go jfsObjects)
  - objects = files; "dir/" keys list by prefix via the namespace itself
  - multipart uploads assemble under /.sys/multipart (gateway.go:188-196)
  - ETag = hex JTH-256 prefix stored in an xattr (etag-in-xattr like the
    reference's s3-etag xattr)

Implements the subset real clients exercise: ListBuckets, Create/Delete
bucket, HeadBucket, ListObjectsV2 (prefix + delimiter + continuation),
Get/Put/Head/Delete/Copy object, and multipart Create/UploadPart/
Complete/Abort. With access/secret keys configured every request is
verified against AWS SigV4 (reference: MinIO auth layer); without them
auth is accepted as-is (trusted boundary / signing proxy).
"""

from __future__ import annotations

import errno as _errno
import posixpath
import re
import urllib.parse
import uuid
from xml.sax.saxutils import escape

from ..meta.types import TYPE_DIRECTORY
from .. import native
from ..tpu.jth256 import digest_hex
from ..utils import get_logger
from ..fs import FSError, FileSystem
from . import BaseHandler, HTTPAdapter

logger = get_logger("gateway.s3")

SYS_MULTIPART = "/.sys/multipart"
ETAG_XATTR = b"s3.etag"
NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _etag(data: bytes) -> str:
    return digest_hex(native.jth256(data))[:32]


class S3Gateway(HTTPAdapter):
    _name = "s3-gateway"

    def __init__(
        self,
        fs: FileSystem,
        address: str = "127.0.0.1",
        port: int = 9000,
        access_key: str = "",
        secret_key: str = "",
    ):
        super().__init__(address, port)
        self.fs = fs
        if access_key:
            from ..object.s3 import SigV4

            self.signer = SigV4(access_key, secret_key)
        else:
            self.signer = None  # trusted-boundary mode: auth accepted as-is
        gw = self

        class Handler(BaseHandler):
            def log_message(self, fmt, *args):
                logger.debug(fmt, *args)

            def _body(self):
                # handlers may run after _authorized already consumed the
                # stream to hash it; serve the cached copy (cleared per
                # request in _authorized)
                cached = getattr(self, "_body_cache", None)
                if cached is None:
                    cached = BaseHandler._body(self)
                    self._body_cache = cached
                return cached

            def _authorized(self) -> bool:
                """Verify AWS SigV4 when the gateway has credentials
                (reference: MinIO auth layer in pkg/gateway): signature,
                payload hash, and a ±15 min date window (replay bound)."""
                self._body_cache = None  # new request on this connection
                if gw.signer is None:
                    return True
                import datetime as _dt
                import hashlib as _hashlib

                headers = {k.lower(): v for k, v in self.headers.items()}
                amz_date = headers.get("x-amz-date", "")
                try:
                    ts = _dt.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                        tzinfo=_dt.timezone.utc
                    )
                except ValueError:
                    self._body()
                    self._error(403, "AccessDenied", "missing x-amz-date")
                    return False
                skew = abs((_dt.datetime.now(_dt.timezone.utc) - ts).total_seconds())
                if skew > 900:
                    self._body()
                    self._error(403, "RequestTimeTooSkewed")
                    return False
                # Payload integrity (ADVICE r2): standard AWS SDK/CLI
                # clients commonly sign UNSIGNED-PAYLOAD — accept it (the
                # signature still covers that literal), verify the hash
                # when one is given, and reject the streaming scheme
                # explicitly instead of failing with a hash mismatch.
                body = self._body()
                content_sha = headers.get("x-amz-content-sha256", "")
                if content_sha.startswith("STREAMING-"):
                    self._error(
                        501, "NotImplemented",
                        "streaming chunked payloads are not supported",
                    )
                    return False
                if content_sha != "UNSIGNED-PAYLOAD" and content_sha != \
                        _hashlib.sha256(body).hexdigest():
                    self._error(400, "XAmzContentSHA256Mismatch")
                    return False
                u = urllib.parse.urlsplit(self.path)
                query = {
                    k: v[0]
                    for k, v in urllib.parse.parse_qs(
                        u.query, keep_blank_values=True
                    ).items()
                }
                ok = gw.signer.verify(
                    self.command,
                    urllib.parse.unquote(u.path),
                    query,
                    headers,
                    self.headers.get("Authorization", ""),
                )
                if not ok:
                    self._error(403, "SignatureDoesNotMatch")
                return ok

            def _params(self):
                u = urllib.parse.urlsplit(self.path)
                q = urllib.parse.parse_qs(u.query, keep_blank_values=True)
                parts = u.path.lstrip("/").split("/", 1)
                bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                return bucket, key, q

            def _xml(self, code: int, body: str):
                data = ('<?xml version="1.0" encoding="UTF-8"?>' + body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, s3code: str, msg: str = ""):
                self._xml(code, f"<Error><Code>{s3code}</Code>"
                                f"<Message>{escape(msg or s3code)}</Message></Error>")

            # -- dispatch --------------------------------------------------
            def do_GET(self):
                if not self._authorized():
                    return
                bucket, key, q = self._params()
                try:
                    if not bucket:
                        return gw._list_buckets(self)
                    if not key:
                        return gw._list_objects(self, bucket, q)
                    return gw._get_object(self, bucket, key)
                except ValueError:
                    self._error(400, "InvalidArgument")
                except FSError as e:
                    self._map_fs_error(e)

            def do_HEAD(self):
                if not self._authorized():
                    return
                bucket, key, q = self._params()
                try:
                    if bucket and not key:
                        gw.fs.stat("/" + bucket)
                        return self._empty(200)
                    return gw._head_object(self, bucket, key)
                except FSError as e:
                    self._empty(404 if e.errno == _errno.ENOENT else 500)

            def do_PUT(self):
                if not self._authorized():
                    return
                bucket, key, q = self._params()
                try:
                    if bucket and not key:
                        return gw._create_bucket(self, bucket)
                    if "partNumber" in q and "uploadId" in q:
                        return gw._upload_part(
                            self, bucket, key, q["uploadId"][0],
                            int(q["partNumber"][0]),
                        )
                    return gw._put_object(self, bucket, key)
                except ValueError:
                    self._error(400, "InvalidArgument")
                except FSError as e:
                    self._map_fs_error(e)

            def do_POST(self):
                if not self._authorized():
                    return
                bucket, key, q = self._params()
                try:
                    if "uploads" in q:
                        return gw._create_multipart(self, bucket, key)
                    if "uploadId" in q:
                        return gw._complete_multipart(self, bucket, key, q["uploadId"][0])
                    self._error(400, "InvalidRequest")
                except ValueError:
                    self._error(400, "InvalidArgument")
                except FSError as e:
                    self._map_fs_error(e)

            def do_DELETE(self):
                if not self._authorized():
                    return
                bucket, key, q = self._params()
                try:
                    if "uploadId" in q:
                        return gw._abort_multipart(self, bucket, key, q["uploadId"][0])
                    if bucket and not key:
                        return gw._delete_bucket(self, bucket)
                    return gw._delete_object(self, bucket, key)
                except ValueError:
                    self._error(400, "InvalidArgument")
                except FSError as e:
                    self._map_fs_error(e)

            def _map_fs_error(self, e: FSError):
                if e.errno == _errno.ENOENT:
                    self._error(404, "NoSuchKey", str(e))
                elif e.errno == _errno.ENOTEMPTY:
                    self._error(409, "BucketNotEmpty", str(e))
                elif e.errno in (_errno.EACCES, _errno.EPERM):
                    self._error(403, "AccessDenied", str(e))
                else:
                    self._error(500, "InternalError", str(e))

        self._handler_cls = Handler

    # -- bucket ops --------------------------------------------------------

    def _list_buckets(self, h):
        entries = self.fs.listdir("/", want_attr=True)
        items = "".join(
            f"<Bucket><Name>{escape(e.name.decode())}</Name>"
            f"<CreationDate>1970-01-01T00:00:00.000Z</CreationDate></Bucket>"
            for e in entries
            if e.attr and e.attr.typ == TYPE_DIRECTORY and not e.name.startswith(b".")
        )
        h._xml(200, f'<ListAllMyBucketsResult xmlns="{NS}">'
                    f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>")

    def _create_bucket(self, h, bucket: str):
        try:
            self.fs.mkdir("/" + bucket, 0o777)
        except FSError as e:
            if e.errno != _errno.EEXIST:
                raise
        h._empty(200, {"Location": "/" + bucket})

    def _delete_bucket(self, h, bucket: str):
        self.fs.rmdir("/" + bucket)
        h._empty(204)

    # -- object ops --------------------------------------------------------

    def _obj_path(self, bucket: str, key: str) -> str:
        p = posixpath.normpath(f"/{bucket}/{key}")
        if not p.startswith(f"/{bucket}/"):
            raise FSError(_errno.EPERM, key)  # path escape attempt
        return p

    def _put_object(self, h, bucket: str, key: str):
        self.fs.stat("/" + bucket)
        data = h._body()
        path = self._obj_path(bucket, key)
        if key.endswith("/"):
            if data:
                raise FSError(_errno.EINVAL, key)
            self.fs.makedirs(path)
            return h._empty(200, {"ETag": '"d41d8cd98f00b204e9800998ecf8427e"'})
        copy_src = h.headers.get("x-amz-copy-source")
        if copy_src:
            src = urllib.parse.unquote(copy_src.lstrip("/"))
            sbucket, _, skey = src.partition("/")
            # Same escape guard as destination keys (no ../ traversal).
            data = self.fs.read_file(self._obj_path(sbucket, skey))
        parent = posixpath.dirname(path)
        if parent != "/":
            self.fs.makedirs(parent)
        et = _etag(data)
        with self.fs.create(path) as f:
            if data:
                f.write(data)
        try:
            self.fs.setxattr(path, ETAG_XATTR, et.encode())
        except FSError:
            pass
        if copy_src:
            return h._xml(200, f'<CopyObjectResult xmlns="{NS}">'
                               f"<ETag>&quot;{et}&quot;</ETag></CopyObjectResult>")
        h._empty(200, {"ETag": f'"{et}"'})

    def _get_object(self, h, bucket: str, key: str):
        path = self._obj_path(bucket, key)
        attr = self.fs.stat(path)
        if attr.typ == TYPE_DIRECTORY:
            raise FSError(_errno.ENOENT, key)
        rng = h.headers.get("Range")
        start, end = 0, attr.length - 1
        code = 200
        if rng and rng.startswith("bytes="):
            try:
                spec = rng[6:].split("-")
                if spec[0]:
                    start = int(spec[0])
                    if spec[1]:
                        end = min(int(spec[1]), attr.length - 1)
                else:  # suffix range
                    start = max(0, attr.length - int(spec[1]))
                code = 206
            except (ValueError, IndexError):
                start, end, code = 0, attr.length - 1, 200  # ignore bad Range
            if code == 206 and start >= attr.length:
                h.send_response(416)
                h.send_header("Content-Range", f"bytes */{attr.length}")
                h.send_header("Content-Length", "0")
                h.end_headers()
                return
            if code == 206 and start > end:
                # syntactically inverted range: unsatisfiable -> ignore
                start, end, code = 0, attr.length - 1, 200
        with self.fs.open(path) as f:
            data = f.pread(start, end - start + 1) if attr.length else b""
        h.send_response(code)
        h.send_header("Content-Type", "application/octet-stream")
        h.send_header("Content-Length", str(len(data)))
        h.send_header("Last-Modified", _http_date(attr.mtime))
        h.send_header("ETag", f'"{self._etag_of(path, attr)}"')
        if code == 206:
            h.send_header("Content-Range", f"bytes {start}-{end}/{attr.length}")
        h.end_headers()
        h.wfile.write(data)

    def _head_object(self, h, bucket: str, key: str):
        path = self._obj_path(bucket, key)
        attr = self.fs.stat(path)
        if attr.typ == TYPE_DIRECTORY and not key.endswith("/"):
            raise FSError(_errno.ENOENT, key)
        h._empty(200, {
            "Content-Length": str(attr.length),
            "Content-Type": "application/octet-stream",
            "Last-Modified": _http_date(attr.mtime),
            "ETag": f'"{self._etag_of(path, attr)}"',
        })

    def _delete_object(self, h, bucket: str, key: str):
        path = self._obj_path(bucket, key)
        try:
            attr = self.fs.stat(path)
            if attr.typ == TYPE_DIRECTORY:
                self.fs.rmdir(path)
            else:
                self.fs.unlink(path)
        except FSError as e:
            if e.errno != _errno.ENOENT:  # S3 delete is idempotent
                raise
        h._empty(204)

    def _etag_of(self, path: str, attr) -> str:
        try:
            return self.fs.getxattr(path, ETAG_XATTR).decode()
        except FSError:
            return f"{attr.length:x}-{attr.mtime:x}"

    # -- listing -----------------------------------------------------------

    def _list_objects(self, h, bucket: str, q):
        self.fs.stat("/" + bucket)
        prefix = q.get("prefix", [""])[0]
        delimiter = q.get("delimiter", [""])[0]
        max_keys = int(q.get("max-keys", ["1000"])[0])
        token = q.get(
            "continuation-token", q.get("start-after", q.get("marker", [""]))
        )[0]

        keys: list[tuple[str, object]] = []
        self._walk(bucket, "", keys, prefix)
        keys.sort(key=lambda kv: kv[0])

        contents, prefixes = [], set()
        truncated, next_token = False, ""
        if max_keys <= 0:
            keys = []
        for key, attr in keys:
            if token and key <= token:
                continue
            if delimiter:
                rest = key[len(prefix):]
                cut = rest.find(delimiter)
                if cut >= 0:
                    prefixes.add(prefix + rest[: cut + 1])
                    continue
            if len(contents) >= max_keys:
                # max_keys >= 1 here, so contents is non-empty: the token is
                # the last key actually returned.
                truncated, next_token = True, contents[-1][0]
                break
            contents.append((key, attr))

        body = "".join(
            f"<Contents><Key>{escape(k)}</Key>"
            f"<LastModified>{_iso_date(a.mtime)}</LastModified>"
            f"<Size>{a.length}</Size>"
            f"<StorageClass>STANDARD</StorageClass></Contents>"
            for k, a in contents
        ) + "".join(
            f"<CommonPrefixes><Prefix>{escape(p)}</Prefix></CommonPrefixes>"
            for p in sorted(prefixes)
        )
        h._xml(200, f'<ListBucketResult xmlns="{NS}">'
                    f"<Name>{escape(bucket)}</Name><Prefix>{escape(prefix)}</Prefix>"
                    f"<KeyCount>{len(contents)}</KeyCount><MaxKeys>{max_keys}</MaxKeys>"
                    f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
                    + (f"<NextContinuationToken>{escape(next_token)}</NextContinuationToken>"
                       if truncated else "")
                    + body + "</ListBucketResult>")

    def _walk(self, bucket: str, rel: str, out: list, prefix: str):
        try:
            entries = self.fs.listdir(f"/{bucket}/{rel}" if rel else f"/{bucket}",
                                      want_attr=True)
        except FSError:
            return
        for e in entries:
            name = e.name.decode()
            key = f"{rel}{name}"
            if e.attr and e.attr.typ == TYPE_DIRECTORY:
                dkey = key + "/"
                # prune subtrees that cannot match the prefix
                if prefix and not dkey.startswith(prefix[: len(dkey)]):
                    continue
                if dkey.startswith(prefix) or prefix.startswith(dkey):
                    # directories are not objects: real S3 lists only keys
                    # (ADVICE r2 — emitting "dir/" entries forced drivers
                    # to guess which trailing-slash keys were markers)
                    self._walk(bucket, dkey, out, prefix)
            elif key.startswith(prefix):
                out.append((key, e.attr))

    # -- multipart ---------------------------------------------------------

    _UPLOAD_ID_RE = re.compile(r"^[0-9a-f]{32}$")

    def _mp_dir(self, upload_id: str) -> str:
        # uploadId is attacker-controlled: only the exact uuid4-hex shape
        # generated by _create_multipart may reach the path join, else
        # '../' ids escape /.sys/multipart (bypassing the _obj_path guard)
        if not self._UPLOAD_ID_RE.fullmatch(upload_id):
            raise ValueError("invalid upload id")
        return f"{SYS_MULTIPART}/{upload_id}"

    def _check_upload_id(self, h, upload_id: str) -> bool:
        if not self._UPLOAD_ID_RE.fullmatch(upload_id):
            h._body()  # drain: an unread body desyncs the keep-alive stream
            h._error(404, "NoSuchUpload", "invalid upload id")
            return False
        return True

    def _create_multipart(self, h, bucket: str, key: str):
        self.fs.stat("/" + bucket)
        upload_id = uuid.uuid4().hex
        self.fs.makedirs(self._mp_dir(upload_id))
        self.fs.write_file(f"{self._mp_dir(upload_id)}/.key",
                           f"{bucket}/{key}".encode())
        h._xml(200, f'<InitiateMultipartUploadResult xmlns="{NS}">'
                    f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
                    f"<UploadId>{upload_id}</UploadId>"
                    f"</InitiateMultipartUploadResult>")

    def _upload_part(self, h, bucket: str, key: str, upload_id: str, num: int):
        if not self._check_upload_id(h, upload_id):
            return
        data = h._body()
        part = f"{self._mp_dir(upload_id)}/{num:05d}"
        self.fs.write_file(part, data)
        h._empty(200, {"ETag": f'"{_etag(data)}"'})

    def _complete_multipart(self, h, bucket: str, key: str, upload_id: str):
        if not self._check_upload_id(h, upload_id):
            return
        h._body()  # part manifest; we assemble all uploaded parts in order
        mp = self._mp_dir(upload_id)
        names = sorted(
            e.name.decode() for e in self.fs.listdir(mp) if e.name != b".key"
        )
        path = self._obj_path(bucket, key)
        parent = posixpath.dirname(path)
        if parent != "/":
            self.fs.makedirs(parent)
        hasher_parts = []
        with self.fs.create(path) as out:
            for n in names:
                data = self.fs.read_file(f"{mp}/{n}")
                hasher_parts.append(_etag(data))
                out.write(data)
        self.fs.remove_all(mp)
        et = _etag("".join(hasher_parts).encode()) + f"-{len(names)}"
        try:
            self.fs.setxattr(path, ETAG_XATTR, et.encode())
        except FSError:
            pass
        h._xml(200, f'<CompleteMultipartUploadResult xmlns="{NS}">'
                    f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
                    f"<ETag>&quot;{et}&quot;</ETag>"
                    f"</CompleteMultipartUploadResult>")

    def _abort_multipart(self, h, bucket: str, key: str, upload_id: str):
        if not self._check_upload_id(h, upload_id):
            return
        try:
            self.fs.remove_all(self._mp_dir(upload_id))
        except FSError:
            pass
        h._empty(204)


def _http_date(ts: int) -> str:
    import email.utils

    return email.utils.formatdate(ts, usegmt=True)


def _iso_date(ts: int) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.000Z")
