"""Gateway serving plane (ISSUE 15 tentpole).

The presentation adapters (S3, WebDAV) used to buffer every object
end-to-end in handler RAM and run on unbounded ``ThreadingHTTPServer``
threads invisible to the QoS scheduler.  This module is the runtime that
turns them into a first-class heavy-traffic entry point:

  admission      a bounded in-flight gate fronting every request:
                 overload sheds IMMEDIATELY as S3 ``503 SlowDown``
                 (never an unbounded queue, never a 500), so the
                 handler-thread population stays bounded by the gate.
  tenancy        SigV4 authentication maps each access key to a tenant
                 uid; every admitted request runs under
                 ``tenant_scope(uid)`` AND against a per-tenant
                 ``FileSystem`` context, so the meta ops and block I/O a
                 request fans out are DRR-queued under the real tenant
                 (qos/scheduler.py) — handler work is FOREGROUND class
                 on the shared lanes like any other entry point.
  streaming      data paths move block-sized spans between the socket
                 and the vfs: GET streams through ``File.pread`` (the
                 PR 10 streaming reader sees the sequential spans and
                 ramps readahead), PUT/UploadPart stream the request
                 body into ``File.write`` (bytes ride the PR 5/8
                 ingest/dedup/compress plane), and at most ONE span per
                 request is ever buffered gateway-side (the
                 ``juicefs_gateway_stream_buffer_bytes`` gauge is the
                 acceptance counter).
  operability    pinned ``juicefs_gateway_*`` metrics and a ``.status``
                 gateway section (in-flight, shed, per-tenant rates,
                 streaming buffers) via ``status_for(vfs)``.

``parse_range`` is the ONE Range-header parser both adapters share
(ISSUE 15 satellite): suffix/inverted/multi-range semantics are defined
(and unit-tested) once.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from contextlib import contextmanager
from typing import Iterator, Optional

from ..fs import FileSystem, FSError
from ..meta.context import Context
from ..meta.types import TYPE_DIRECTORY
from ..metric import global_registry
from ..qos import tenant_scope
from ..tpu.jth256 import digest_hex
from .. import native
from ..utils import get_logger

logger = get_logger("gateway.serve")

_reg = global_registry()
_REQUESTS = _reg.counter(
    "juicefs_gateway_requests",
    "Requests admitted by the gateway serving plane", ("op",),
)
_SHED = _reg.counter(
    "juicefs_gateway_shed",
    "Requests shed as 503 SlowDown by the admission gate",
)
_ERRORS = _reg.counter(
    "juicefs_gateway_errors",
    "Error responses sent by the gateway", ("family",),
)
_AUTH_FAILURES = _reg.counter(
    "juicefs_gateway_auth_failures",
    "Requests rejected by the SigV4 authenticator",
)
_BYTES_IN = _reg.counter(
    "juicefs_gateway_bytes_in",
    "Object bytes streamed from clients into the volume",
)
_BYTES_OUT = _reg.counter(
    "juicefs_gateway_bytes_out",
    "Object bytes streamed from the volume to clients",
)
_REQ_SECONDS = _reg.histogram(
    "juicefs_gateway_request_seconds",
    "Admitted-request wall time per op", ("op",),
)

# live planes for the process-level gauges + the per-vfs .status section
_LIVE_PLANES: "weakref.WeakSet[ServingPlane]" = weakref.WeakSet()


def _sum_planes(fn) -> float:
    total = 0
    try:
        for p in list(_LIVE_PLANES):
            total += fn(p)
    except Exception as e:
        # racing a plane teardown must never break a scrape
        logger.debug("gateway gauge scrape raced a teardown: %s", e)
    return total


_reg.gauge(
    "juicefs_gateway_inflight",
    "Requests currently inside the admission gate",
).set_function(lambda: _sum_planes(lambda p: p.gate.inflight))
_reg.gauge(
    "juicefs_gateway_stream_buffer_bytes",
    "Gateway-side streaming buffer bytes currently held "
    "(bounded: one block-sized span per admitted request)",
).set_function(lambda: _sum_planes(lambda p: p._buffered))


# ---------------------------------------------------------------- ranges --

UNSATISFIABLE = object()  # parse_range sentinel: respond 416


def parse_range(rng: Optional[str], total: int):
    """The ONE RFC 7233 Range parser both adapters use.

    Returns ``None`` (serve the full body, 200), ``(start, end)``
    inclusive (206), or the ``UNSATISFIABLE`` sentinel (416).  Semantics
    shared by S3 and WebDAV:

      - only single ``bytes=`` ranges; a multi-range spec (comma) is
        IGNORED (RFC 7233 lets a server serve the full representation);
      - malformed or syntactically inverted specs are ignored;
      - ``bytes=a-b`` clamps ``b`` to the last byte;
      - ``bytes=a-`` with ``a >= total`` is unsatisfiable;
      - suffix ``bytes=-N`` takes the last N bytes; ``-0`` (and any
        range against an empty body) is unsatisfiable per the RFC.
    """
    if not rng or not rng.startswith("bytes=") or "," in rng:
        return None
    spec = rng[6:].strip()
    a, sep, b = spec.partition("-")
    if not sep:
        return None
    try:
        if a:
            start = int(a)
            if start < 0:
                return None
            if b:
                end = int(b)
                if end < start:
                    return None  # inverted: ignore the header
                end = min(end, total - 1)
            else:
                end = total - 1
            if start >= total:
                return UNSATISFIABLE
            return start, end
        # suffix-range: last N bytes; N must be a plain non-negative int
        if not b.isdigit():
            return None
        n = int(b)
        if n == 0 or total == 0:
            return UNSATISFIABLE
        return max(0, total - n), total - 1
    except ValueError:
        return None  # malformed: ignore the header (RFC 7233)


# ------------------------------------------------------------- streaming --

def stream_file_out(wfile, f, start: int, length: int, span: int,
                    account=None) -> int:
    """Stream ``length`` bytes of open file ``f`` from ``start`` to the
    socket in ``span``-sized pieces.  Each piece rides ``File.pread`` —
    the vfs streaming reader sees the sequential spans and ramps its
    readahead window (ISSUE 11) — and is released from the gateway-side
    buffer before the next is read (bounded per-request buffering).
    Returns bytes actually written; a short vfs read (file truncated
    mid-stream) stops early — the caller must close the connection so
    the client sees the truncation instead of a hung keep-alive."""
    sent = 0
    span = max(1, span)
    while sent < length:
        n = min(span, length - sent)
        data = f.pread(start + sent, n)
        if not data:
            break
        if account is not None:
            account(len(data))
        try:
            wfile.write(data)
        finally:
            if account is not None:
                account(-len(data))
        _BYTES_OUT.inc(len(data))
        sent += len(data)
        if len(data) < n:
            break
    return sent


class StreamingEtag:
    """Incremental JTH-256 ETag over streamed spans.

    A body that fits one span hashes exactly like the buffered seed path
    (``jth256(data)``); a larger stream folds the per-span digests into
    a tree digest (the same shape multipart ETags already have — the
    value is opaque to clients, stored in the etag xattr)."""

    def __init__(self):
        self._first: Optional[bytes] = None
        self._tree = None
        self._spans = 0

    def update(self, piece: bytes) -> None:
        self._spans += 1
        if self._spans == 1:
            self._first = bytes(piece)
            return
        if self._tree is None:
            self._tree = hashlib.sha256()  # fold carrier for span digests
            self._tree.update(native.jth256(self._first))
            self._first = None
        self._tree.update(native.jth256(bytes(piece)))

    def hexdigest(self) -> str:
        if self._tree is not None:
            return digest_hex(native.jth256(self._tree.digest()))[:32]
        return digest_hex(native.jth256(self._first or b""))[:32]


def stream_body_in(rfile, f, length: int, span: int, account=None,
                   want_sha: Optional[str] = None, consumed=None):
    """Stream ``length`` request-body bytes into open file ``f`` in
    ``span``-sized pieces, so the bytes ride the vfs write pipeline
    (slice-building, inline dedup, batched compression) instead of one
    end-to-end RAM buffer.  Returns ``(etag_hex, bytes_read, sha_ok)``:
    ``bytes_read < length`` means the client truncated the body;
    ``sha_ok`` is False when ``want_sha`` (a signed x-amz-content-sha256)
    does not match the streamed payload — the caller unwinds the write.
    ``consumed`` (the handler's body accounting) is credited piece by
    piece AS the socket is read, never post-hoc: a mid-stream vfs write
    failure must not leave the error path believing the body is still
    unread (its drain would block on bytes that never come, then eat
    the next pipelined request)."""
    etag = StreamingEtag()
    sha = hashlib.sha256() if want_sha else None
    got = 0
    span = max(1, span)
    while got < length:
        piece = rfile.read(min(span, length - got))
        if not piece:
            break
        if consumed is not None:
            consumed(len(piece))
        if account is not None:
            account(len(piece))
        try:
            etag.update(piece)
            if sha is not None:
                sha.update(piece)
            f.write(piece)
        finally:
            if account is not None:
                account(-len(piece))
        _BYTES_IN.inc(len(piece))
        got += len(piece)
    sha_ok = sha is None or sha.hexdigest() == want_sha
    return etag.hexdigest(), got, sha_ok


# ------------------------------------------------------------- admission --

class AdmissionGate:
    """Bounded in-flight admission: overload sheds, never queues.

    ``max_inflight`` bounds the requests concurrently past the gate (and
    with them the handler threads doing real work); a request arriving
    at the bound is refused immediately — the adapter turns that into
    S3 ``503 SlowDown`` — so a traffic spike degrades into counted,
    retryable sheds instead of an unbounded thread/queue pileup."""

    def __init__(self, max_inflight: int = 64):
        self.max_inflight = max(1, int(max_inflight))
        self._lock = threading.Lock()
        self.inflight = 0
        self.admitted = 0
        self.shed = 0

    def try_enter(self) -> bool:
        with self._lock:
            if self.inflight >= self.max_inflight:
                self.shed += 1
                return False
            self.inflight += 1
            self.admitted += 1
            return True

    def leave(self) -> None:
        with self._lock:
            self.inflight -= 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"max_inflight": self.max_inflight,
                    "inflight": self.inflight,
                    "admitted": self.admitted, "shed": self.shed}


# ---------------------------------------------------------------- tenancy --

# synthetic uid base for access-key tenants: far above real system uids
# so gateway tenants never collide with FUSE users in the DRR queues
TENANT_UID_BASE = 3_000_000


def tenant_uid(access_key: str) -> int:
    """Deterministic tenant uid for an access key: STABLE across gateway
    restarts and adapter instances (arrival-order assignment would remap
    file ownership and the DRR fair-queue identity on every restart).
    Stays under 2^31 (kernel uid space); a hash collision merely makes
    two keys share a fair queue and ownership — safe, and vanishingly
    rare at realistic key counts."""
    h = int.from_bytes(
        hashlib.sha256(access_key.encode()).digest()[:4], "big")
    return TENANT_UID_BASE + h % 1_000_000_000


class Tenant:
    """One authenticated principal: its access key, uid, and the
    FileSystem context every op of its requests runs under."""

    __slots__ = ("name", "uid", "fs")

    def __init__(self, name: str, uid: int, fs: FileSystem):
        self.name = name
        self.uid = uid
        self.fs = fs


class GatewayAuth:
    """SigV4 verification over a MULTI-key registry: each access key is
    its own tenant (reference: MinIO's auth layer fronting pkg/gateway).
    With no keys registered the gateway runs in trusted-boundary mode
    (auth accepted as-is, single anonymous tenant)."""

    def __init__(self):
        self._signers: dict[str, object] = {}

    def add_key(self, access_key: str, secret_key: str) -> None:
        from ..object.s3 import SigV4

        self._signers[access_key] = SigV4(access_key, secret_key)

    @property
    def enabled(self) -> bool:
        return bool(self._signers)

    def access_keys(self) -> list[str]:
        return sorted(self._signers)

    def verify(self, method: str, path: str, query: dict,
               headers: dict, authorization: str) -> Optional[str]:
        """Returns the authenticated ACCESS KEY, or None."""
        try:
            cred = dict(
                p.strip().split("=", 1)
                for p in authorization.split(" ", 1)[1].split(",")
            )["Credential"].split("/")[0]
        except (KeyError, IndexError, ValueError):
            return None
        signer = self._signers.get(cred)
        if signer is None:
            return None
        if signer.verify(method, path, query, headers, authorization):
            return cred
        return None


# ------------------------------------------------------------ key walker --

class OrderedKeyWalker:
    """Lexicographic, resumable object-key stream over one bucket.

    ListObjectsV2 at scale (ISSUE 15): keys stream in S3 sort order from
    an incremental directory walk — one listing per directory actually
    entered, never a full-bucket recursion — so memory at any page size
    is bounded by (directory fan-out x depth), not bucket size.

      prefix   only keys starting with it; subtrees that cannot match
               are pruned without being listed
      after    strictly-greater resumption bound (continuation-token /
               start-after / marker): subtrees entirely <= after are
               pruned without being listed
      skip     settable mid-iteration: while a key starts with it, the
               walker discards without yielding and prunes whole
               directories under it — how the delimiter roll-up skips
               a CommonPrefixes subtree it will never emit from

    Ordering subtlety: entries sort by ``name + '/'`` for directories
    (a directory's keys all carry the trailing slash, so ``foo.txt``
    must sort BEFORE the subtree of directory ``foo`` — byte 0x2e < 0x2f
    — which a bare name sort gets wrong)."""

    def __init__(self, fs: FileSystem, bucket: str, prefix: str = "",
                 after: str = ""):
        self.fs = fs
        self.bucket = bucket
        self.prefix = prefix
        self.after = after
        # a common-prefix continuation token must ALSO skip its whole
        # subtree — but only the handler knows the delimiter (a bare
        # start-after that happens to end with "/" still lists the keys
        # inside), so the handler sets `skip`, never the constructor
        self.skip = ""

    def __iter__(self) -> Iterator[tuple[str, object]]:
        return self._walk("")

    def _walk(self, rel: str) -> Iterator[tuple[str, object]]:
        try:
            entries = self.fs.listdir(
                f"/{self.bucket}/{rel}" if rel else f"/{self.bucket}",
                want_attr=True,
            )
        except FSError:
            return
        items = []
        for e in entries:
            # dotted names are ordinary S3 keys: the multipart staging
            # area (/.sys) is a sibling of the buckets at the VOLUME
            # root, never inside one, so nothing here needs hiding
            name = e.name.decode()
            is_dir = bool(e.attr and e.attr.typ == TYPE_DIRECTORY)
            items.append((name + "/" if is_dir else name, name, is_dir, e))
        items.sort(key=lambda it: it[0])
        for _sort_key, name, is_dir, e in items:
            key = rel + name
            if is_dir:
                dkey = key + "/"
                # prune: cannot match the prefix, entirely consumed by
                # the resumption bound, or inside the skip subtree
                if self.prefix and not (dkey.startswith(self.prefix)
                                        or self.prefix.startswith(dkey)):
                    continue
                if self.after and not (dkey > self.after
                                       or self.after.startswith(dkey)):
                    continue
                if self.skip and dkey.startswith(self.skip):
                    continue
                yield from self._walk(dkey)
            else:
                if key <= self.after or not key.startswith(self.prefix):
                    continue
                if self.skip and key.startswith(self.skip):
                    continue
                yield key, e.attr


# ------------------------------------------------------------- the plane --

class ServingPlane:
    """Per-gateway runtime: admission, tenancy, stream accounting, and
    the ``.status`` gateway section.  One per adapter instance; all
    planes over one vfs aggregate in ``status_for``."""

    def __init__(self, vfs, auth: Optional[GatewayAuth] = None,
                 max_inflight: int = 64):
        self.vfs = vfs
        self.auth = auth or GatewayAuth()
        self.gate = AdmissionGate(max_inflight)
        # per-request streaming budget: the helpers hold at most ONE
        # span of block_size bytes at a time (the acceptance bound)
        self.span = int(vfs.store.conf.block_size)
        self._lock = threading.Lock()
        self._buffered = 0
        self.buffered_peak = 0
        self._tenants: dict[str, Tenant] = {}
        self._tenant_ops: dict[str, int] = {}
        self._requests: dict[str, int] = {}
        _LIVE_PLANES.add(self)

    # -- tenancy -----------------------------------------------------------
    def bind_anonymous(self, fs: FileSystem) -> Tenant:
        """Trusted-boundary principal: serves through the CALLER's
        FileSystem context instead of a synthetic tenant uid."""
        with self._lock:
            t = Tenant("anonymous", getattr(fs.ctx, "uid", 0), fs)
            self._tenants[""] = t
            return t

    def tenant(self, name: str) -> Tenant:
        """Get-or-create the tenant context for an access key (or the
        anonymous principal in trusted-boundary mode)."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                uid = 0 if name == "" else tenant_uid(name)
                fs = FileSystem(self.vfs, Context(uid=uid, gid=uid, pid=0))
                t = self._tenants[name] = Tenant(name or "anonymous", uid, fs)
            return t

    # -- admission ---------------------------------------------------------
    @contextmanager
    def admitted(self, op: str, tenant: Optional[Tenant] = None):
        """Admission scope around one request's dispatch: sheds at the
        gate (yields None — the adapter answers 503 SlowDown), else runs
        the body FOREGROUND under the tenant's scope so every meta op
        and block I/O it fans out lands in the tenant's DRR queue."""
        import time as _time

        if not self.gate.try_enter():
            _SHED.inc()
            yield None
            return
        _REQUESTS.labels(op).inc()
        uid = tenant.uid if tenant is not None else 0
        name = tenant.name if tenant is not None else "anonymous"
        with self._lock:
            self._requests[op] = self._requests.get(op, 0) + 1
            self._tenant_ops[name] = self._tenant_ops.get(name, 0) + 1
        t0 = _time.perf_counter()
        try:
            with tenant_scope(uid):
                yield self
        finally:
            self.gate.leave()
            _REQ_SECONDS.labels(op).observe(_time.perf_counter() - t0)

    # -- stream accounting -------------------------------------------------
    def _account(self, delta: int) -> None:
        with self._lock:
            self._buffered += delta
            if self._buffered > self.buffered_peak:
                self.buffered_peak = self._buffered

    def stream_out(self, wfile, f, start: int, length: int) -> int:
        return stream_file_out(wfile, f, start, length, self.span,
                               account=self._account)

    def write_span(self, wfile, data) -> int:
        """Write one already-read span with buffer accounting (the
        pre-header first span of a GET)."""
        if not data:
            return 0
        self._account(len(data))
        try:
            wfile.write(data)
        finally:
            self._account(-len(data))
        _BYTES_OUT.inc(len(data))
        return len(data)

    def stream_in(self, handler, f, length: int,
                  want_sha: Optional[str] = None):
        """Stream the handler's request body into ``f``, crediting the
        handler's consumed-byte accounting per piece (so its error-path
        drain stays exact even when the vfs write dies mid-stream)."""
        return stream_body_in(handler.rfile, f, length, self.span,
                              account=self._account, want_sha=want_sha,
                              consumed=handler._note_consumed)

    # -- observability -----------------------------------------------------
    def note_error(self, code: int) -> None:
        if code >= 400:
            _ERRORS.labels("5xx" if code >= 500 else "4xx").inc()

    def note_auth_failure(self) -> None:
        _AUTH_FAILURES.inc()

    def stats(self) -> dict:
        with self._lock:
            return {
                "admission": self.gate.snapshot(),
                "requests": dict(self._requests),
                "tenants": dict(self._tenant_ops),
                "streaming": {
                    "span_bytes": self.span,
                    "window_bytes": self.span,
                    "buffered_bytes": self._buffered,
                    "buffered_peak": self.buffered_peak,
                },
                "auth": {"enabled": self.auth.enabled,
                         "keys": len(self.auth.access_keys())},
            }


def status_for(vfs) -> Optional[dict]:
    """Aggregate ``.status`` gateway section for every live plane over
    this vfs (vfs/internal.py consults it; None = no gateway attached)."""
    planes = [p for p in list(_LIVE_PLANES) if p.vfs is vfs]
    if not planes:
        return None
    if len(planes) == 1:
        return planes[0].stats()
    return {"adapters": [p.stats() for p in planes]}
