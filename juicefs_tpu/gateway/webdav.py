"""WebDAV server over the FileSystem SDK (reference pkg/fs/http.go:84
webdavFS over golang.org/x/net/webdav).

Class-1 DAV: OPTIONS, PROPFIND (depth 0/1), GET/HEAD/PUT/DELETE, MKCOL,
MOVE, COPY — the operations litmus and common DAV clients (davfs2, cadaver,
macOS Finder) use for file management.
"""

from __future__ import annotations

import errno as _errno
import posixpath
import urllib.parse
from xml.sax.saxutils import escape

from ..meta.types import TYPE_DIRECTORY
from ..utils import get_logger
from ..fs import FSError, FileSystem
from . import BaseHandler, HTTPAdapter

logger = get_logger("gateway.webdav")


class WebDAVServer(HTTPAdapter):
    _name = "webdav"

    def __init__(self, fs: FileSystem, address: str = "127.0.0.1", port: int = 9007):
        super().__init__(address, port)
        self.fs = fs
        dav = self

        class Handler(BaseHandler):
            def log_message(self, fmt, *args):
                logger.debug(fmt, *args)

            def _path(self) -> str:
                return urllib.parse.unquote(
                    urllib.parse.urlsplit(self.path).path
                ) or "/"

            def _err(self, e: FSError):
                code = {
                    _errno.ENOENT: 404,
                    _errno.EEXIST: 405,
                    _errno.ENOTEMPTY: 409,
                    _errno.EACCES: 403,
                    _errno.EPERM: 403,
                    _errno.EISDIR: 405,
                    _errno.ENOTDIR: 409,
                }.get(e.errno, 500)
                self._empty(code)

            def do_OPTIONS(self):
                self._empty(200, {"DAV": "1,2", "Allow":
                                  "OPTIONS, PROPFIND, GET, HEAD, PUT, DELETE, "
                                  "MKCOL, MOVE, COPY"})

            def do_PROPFIND(self):
                self._body()
                path = self._path()
                depth = self.headers.get("Depth", "1")
                try:
                    items = dav._propfind(path, depth)
                except FSError as e:
                    return self._err(e)
                body = ('<?xml version="1.0" encoding="utf-8"?>'
                        '<D:multistatus xmlns:D="DAV:">' + "".join(items) +
                        "</D:multistatus>").encode()
                self.send_response(207)
                self.send_header("Content-Type", 'application/xml; charset="utf-8"')
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    attr = dav.fs.stat(self._path())
                    if attr.typ == TYPE_DIRECTORY:
                        return self._empty(405)
                    data = dav.fs.read_file(self._path())
                except FSError as e:
                    return self._err(e)
                # RFC 7233 single byte-range (bytes=a-b / bytes=a- ); an
                # invalid spec (inverted or unparsable) ignores the header
                start = None
                rng = self.headers.get("Range", "")
                if rng.startswith("bytes=") and "," not in rng:
                    total = len(data)
                    try:
                        a, _, b = rng[6:].partition("-")
                        if a and b:
                            s, e = int(a), min(int(b), total - 1)
                            valid = s >= 0 and int(b) >= s  # inverted -> ignore
                        elif a:
                            s, e = int(a), total - 1
                            valid = s >= 0
                        else:
                            # suffix-range: last N bytes; N must be a plain
                            # non-negative integer or the spec is invalid
                            valid = b.isdigit()
                            s, e = (max(0, total - int(b)), total - 1) if valid else (0, 0)
                        if valid:
                            if s >= total:
                                return self._empty(416)  # unsatisfiable
                            start, end = s, e
                    except ValueError:
                        pass  # malformed: ignore the header (RFC 7233)
                if start is not None:
                    part = data[start:end + 1]
                    self.send_response(206)
                    self.send_header("Content-Range",
                                     f"bytes {start}-{end}/{total}")
                    self.send_header("Content-Length", str(len(part)))
                    self.end_headers()
                    self.wfile.write(part)
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_HEAD(self):
                try:
                    attr = dav.fs.stat(self._path())
                except FSError as e:
                    return self._err(e)
                self._empty(200, {"Content-Length": str(attr.length)})

            def do_PUT(self):
                data = self._body()
                path = self._path()
                try:
                    parent = posixpath.dirname(path.rstrip("/"))
                    if parent and parent != "/" and not dav.fs.exists(parent):
                        return self._empty(409)  # RFC: no implicit collections
                    dav.fs.write_file(path, data)
                except FSError as e:
                    return self._err(e)
                self._empty(201)

            def do_DELETE(self):
                path = self._path()
                try:
                    if not dav.fs.exists(path):
                        return self._empty(404)  # RFC 4918: missing -> 404
                    dav.fs.remove_all(path)
                except FSError as e:
                    return self._err(e)
                self._empty(204)

            def do_MKCOL(self):
                if self._body():
                    return self._empty(415)
                try:
                    dav.fs.mkdir(self._path().rstrip("/"))
                except FSError as e:
                    if e.errno == _errno.ENOENT:
                        return self._empty(409)  # missing parent (RFC 4918)
                    if e.errno == _errno.EEXIST:
                        return self._empty(405)  # already exists (RFC 4918)
                    return self._err(e)
                self._empty(201)

            def _dest(self) -> str | None:
                dst = self.headers.get("Destination")
                if not dst:
                    return None
                return urllib.parse.unquote(urllib.parse.urlsplit(dst).path)

            def do_MOVE(self):
                dst = self._dest()
                if not dst:
                    return self._empty(400)
                try:
                    overwrote = dav.fs.exists(dst)
                    if overwrote:
                        if self.headers.get("Overwrite", "T") == "F":
                            return self._empty(412)
                        dav.fs.remove_all(dst)
                    dav.fs.rename(self._path().rstrip("/"), dst.rstrip("/"))
                except FSError as e:
                    return self._err(e)
                self._empty(204 if overwrote else 201)

            def do_COPY(self):
                dst = self._dest()
                if not dst:
                    return self._empty(400)
                try:
                    attr = dav.fs.stat(self._path())
                    if attr.typ == TYPE_DIRECTORY:
                        return self._empty(403)  # file copies only
                    overwrote = dav.fs.exists(dst)
                    if overwrote and self.headers.get("Overwrite", "T") == "F":
                        return self._empty(412)
                    dav.fs.write_file(dst, dav.fs.read_file(self._path()))
                except FSError as e:
                    return self._err(e)
                self._empty(204 if overwrote else 201)

        self._handler_cls = Handler

    def _propfind(self, path: str, depth: str) -> list[str]:
        attr = self.fs.stat(path)
        items = [self._propstat(path, attr)]
        if depth != "0" and attr.typ == TYPE_DIRECTORY:
            for e in self.fs.listdir(path, want_attr=True):
                child = posixpath.join(path, e.name.decode())
                if e.attr is not None:
                    items.append(self._propstat(child, e.attr))
        return items

    @staticmethod
    def _propstat(path: str, attr) -> str:
        is_dir = attr.typ == TYPE_DIRECTORY
        href = urllib.parse.quote(path + ("/" if is_dir and path != "/" else ""))
        rtype = "<D:collection/>" if is_dir else ""
        length = "" if is_dir else f"<D:getcontentlength>{attr.length}</D:getcontentlength>"
        return (f"<D:response><D:href>{escape(href)}</D:href><D:propstat><D:prop>"
                f"<D:resourcetype>{rtype}</D:resourcetype>{length}"
                f"</D:prop><D:status>HTTP/1.1 200 OK</D:status></D:propstat>"
                f"</D:response>")

