"""WebDAV server over the FileSystem SDK (reference pkg/fs/http.go:84
webdavFS over golang.org/x/net/webdav).

Class-1 DAV: OPTIONS, PROPFIND (depth 0/1), GET/HEAD/PUT/DELETE, MKCOL,
MOVE, COPY — the operations litmus and common DAV clients (davfs2, cadaver,
macOS Finder) use for file management.
"""

from __future__ import annotations

import errno as _errno
import posixpath
import urllib.parse
from xml.sax.saxutils import escape

from ..meta.types import TYPE_DIRECTORY
from ..utils import get_logger
from ..fs import FSError, FileSystem
from . import BaseHandler, HTTPAdapter
from .serve import UNSATISFIABLE, parse_range, stream_body_in, stream_file_out

logger = get_logger("gateway.webdav")


class WebDAVServer(HTTPAdapter):
    _name = "webdav"

    def __init__(self, fs: FileSystem, address: str = "127.0.0.1", port: int = 9007):
        super().__init__(address, port)
        self.fs = fs
        dav = self

        class Handler(BaseHandler):
            def log_message(self, fmt, *args):
                logger.debug(fmt, *args)

            def _path(self) -> str:
                return urllib.parse.unquote(
                    urllib.parse.urlsplit(self.path).path
                ) or "/"

            def _err(self, e: FSError):
                code = {
                    _errno.ENOENT: 404,
                    _errno.EEXIST: 405,
                    _errno.ENOTEMPTY: 409,
                    _errno.EACCES: 403,
                    _errno.EPERM: 403,
                    _errno.EISDIR: 405,
                    _errno.ENOTDIR: 409,
                }.get(e.errno, 500)
                self._empty(code)

            def do_OPTIONS(self):
                self._empty(200, {"DAV": "1,2", "Allow":
                                  "OPTIONS, PROPFIND, GET, HEAD, PUT, DELETE, "
                                  "MKCOL, MOVE, COPY"})

            def do_PROPFIND(self):
                self._body()
                path = self._path()
                depth = self.headers.get("Depth", "1")
                try:
                    items = dav._propfind(path, depth)
                except FSError as e:
                    return self._err(e)
                body = ('<?xml version="1.0" encoding="utf-8"?>'
                        '<D:multistatus xmlns:D="DAV:">' + "".join(items) +
                        "</D:multistatus>").encode()
                self.send_response(207)
                self.send_header("Content-Type", 'application/xml; charset="utf-8"')
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                """Streaming GET on the same read path as the S3 gateway
                (ISSUE 15 satellite): block-sized spans ride the vfs
                streaming reader — never one whole-object RAM buffer —
                and Range semantics come from the ONE shared parser
                (gateway/serve.py parse_range)."""
                try:
                    attr = dav.fs.stat(self._path())
                    if attr.typ == TYPE_DIRECTORY:
                        return self._empty(405)
                except FSError as e:
                    return self._err(e)
                total = attr.length
                rng = parse_range(self.headers.get("Range", ""), total)
                if rng is UNSATISFIABLE:
                    return self._empty(416)
                if rng is None:
                    start, end, code = 0, total - 1, 200
                else:
                    (start, end), code = rng, 206
                length = end - start + 1 if total else 0
                if not length:
                    self.send_response(code)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                # first span BEFORE the headers commit: a failing read
                # still maps to a clean error; only a mid-stream failure
                # degrades to a closed connection
                try:
                    with dav.fs.open(self._path()) as f:
                        span = dav._span()
                        first = f.pread(start, min(span, length))
                        self.send_response(code)
                        if code == 206:
                            self.send_header(
                                "Content-Range",
                                f"bytes {start}-{end}/{total}")
                        self.send_header("Content-Length", str(length))
                        self.end_headers()
                        self.wfile.write(first)
                        sent = len(first)
                        if sent == len(first) and sent < length:
                            try:
                                sent += stream_file_out(
                                    self.wfile, f, start + sent,
                                    length - sent, span)
                            except OSError:
                                pass  # headers committed: close below
                except FSError as e:
                    return self._err(e)
                if sent < length:
                    self.close_connection = True  # truncated mid-stream

            def do_HEAD(self):
                try:
                    attr = dav.fs.stat(self._path())
                except FSError as e:
                    return self._err(e)
                self._empty(200, {"Content-Length": str(attr.length)})

            def do_PUT(self):
                """Streaming PUT: the body flows into the vfs writer in
                block-sized pieces (ingest/dedup/compress engage), same
                data path as S3 PUT — including the temp+rename publish,
                so a failed overwrite never destroys the previous
                version of the resource."""
                import uuid as _uuid

                path = self._path()
                length = self._remaining()
                tmp = f"/.sys/tmp/{_uuid.uuid4().hex}"
                try:
                    parent = posixpath.dirname(path.rstrip("/"))
                    if parent and parent != "/" and not dav.fs.exists(parent):
                        self._drain()
                        return self._empty(409)  # RFC: no implicit collections
                    dav.fs.makedirs("/.sys/tmp")
                    with dav.fs.create(tmp) as f:
                        _et, got, _ok = stream_body_in(
                            self.rfile, f, length, dav._span(),
                            consumed=self._note_consumed)
                except FSError as e:
                    self._drain()
                    dav._discard(tmp)
                    return self._err(e)
                if got < length:
                    # client truncated the body: drop the temp and the
                    # (desynced) connection — the live resource, if
                    # any, is untouched
                    dav._discard(tmp)
                    self.close_connection = True
                    return self._empty(400)
                try:
                    dav.fs.rename(tmp, path)
                except FSError as e:
                    dav._discard(tmp)
                    return self._err(e)
                self._empty(201)

            def do_DELETE(self):
                path = self._path()
                try:
                    if not dav.fs.exists(path):
                        return self._empty(404)  # RFC 4918: missing -> 404
                    dav.fs.remove_all(path)
                except FSError as e:
                    return self._err(e)
                self._empty(204)

            def do_MKCOL(self):
                if self._body():
                    return self._empty(415)
                try:
                    dav.fs.mkdir(self._path().rstrip("/"))
                except FSError as e:
                    if e.errno == _errno.ENOENT:
                        return self._empty(409)  # missing parent (RFC 4918)
                    if e.errno == _errno.EEXIST:
                        return self._empty(405)  # already exists (RFC 4918)
                    return self._err(e)
                self._empty(201)

            def _dest(self) -> str | None:
                dst = self.headers.get("Destination")
                if not dst:
                    return None
                return urllib.parse.unquote(urllib.parse.urlsplit(dst).path)

            def do_MOVE(self):
                dst = self._dest()
                if not dst:
                    return self._empty(400)
                try:
                    overwrote = dav.fs.exists(dst)
                    if overwrote:
                        if self.headers.get("Overwrite", "T") == "F":
                            return self._empty(412)
                        dav.fs.remove_all(dst)
                    dav.fs.rename(self._path().rstrip("/"), dst.rstrip("/"))
                except FSError as e:
                    return self._err(e)
                self._empty(204 if overwrote else 201)

            def do_COPY(self):
                dst = self._dest()
                if not dst:
                    return self._empty(400)
                try:
                    attr = dav.fs.stat(self._path())
                    if attr.typ == TYPE_DIRECTORY:
                        return self._empty(403)  # file copies only
                    overwrote = dav.fs.exists(dst)
                    if overwrote and self.headers.get("Overwrite", "T") == "F":
                        return self._empty(412)
                    if dst == self._path():
                        # copy onto itself: truncating the destination
                        # would destroy the source — a no-op replace
                        return self._empty(204)
                    # server-side slice share: no data bytes move
                    with dav.fs.create(dst):
                        pass
                    dav.fs.copy_range(self._path(), dst)
                except FSError as e:
                    return self._err(e)
                self._empty(204 if overwrote else 201)

        self._handler_cls = Handler

    def _span(self) -> int:
        """Streaming span: one block per piece, the same granularity the
        chunk store caches and the readahead window grows by."""
        return int(self.fs.vfs.store.conf.block_size)

    def _discard(self, path: str) -> None:
        try:
            self.fs.unlink(path)
        except FSError:
            pass  # unwind of a failed PUT: the temp may never have landed

    def _propfind(self, path: str, depth: str) -> list[str]:
        attr = self.fs.stat(path)
        items = [self._propstat(path, attr)]
        if depth != "0" and attr.typ == TYPE_DIRECTORY:
            for e in self.fs.listdir(path, want_attr=True):
                child = posixpath.join(path, e.name.decode())
                if e.attr is not None:
                    items.append(self._propstat(child, e.attr))
        return items

    @staticmethod
    def _propstat(path: str, attr) -> str:
        is_dir = attr.typ == TYPE_DIRECTORY
        href = urllib.parse.quote(path + ("/" if is_dir and path != "/" else ""))
        rtype = "<D:collection/>" if is_dir else ""
        length = "" if is_dir else f"<D:getcontentlength>{attr.length}</D:getcontentlength>"
        return (f"<D:response><D:href>{escape(href)}</D:href><D:propstat><D:prop>"
                f"<D:resourcetype>{rtype}</D:resourcetype>{length}"
                f"</D:prop><D:status>HTTP/1.1 200 OK</D:status></D:propstat>"
                f"</D:response>")

