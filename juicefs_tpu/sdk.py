"""Flat call surface for the libjfs C ABI (reference sdk/java/libjfs/
main.go:409-900: the Go c-shared layer keeps a per-mount wrapper table
and exposes `jfs_*` functions; here the C shim in sdk/c/libjfs.cpp embeds
CPython and calls these functions, which do all marshalling in Python so
the C side stays a thin trampoline).

Conventions (mirroring the reference C ABI):
  - every function returns >= 0 on success or -errno on failure;
  - mounts and open files are referenced by small integer ids;
  - paths are UTF-8 strings, data moves as bytes.
"""

from __future__ import annotations

import errno as _errno
import os
import threading

_lock = threading.Lock()
_mounts: dict[int, tuple] = {}   # mid -> (FileSystem, VFS, meta)
_files: dict[tuple[int, int], object] = {}  # (mid, fd) -> File
_next_mid = 1
_next_fd = 1


def _fs(mid: int):
    ent = _mounts.get(mid)
    if ent is None:
        raise OSError(_errno.EBADF, "bad mount id")
    return ent[0]


def _file(mid: int, fd: int):
    f = _files.get((mid, fd))
    if f is None:
        raise OSError(_errno.EBADF, "bad file id")
    return f


def _wrap(fn):
    """Map FSError/OSError to -errno for the C boundary."""
    def run(*args):
        try:
            out = fn(*args)
            return 0 if out is None else out
        except OSError as e:
            return -(e.errno or _errno.EIO)
        except Exception:
            import traceback

            traceback.print_exc()
            return -_errno.EIO
    return run


@_wrap
def jfs_init(meta_url: str) -> int:
    """Open a volume; returns a mount id (reference jfs_init main.go:409)."""
    global _next_mid
    from .chunk import CachedStore, ChunkConfig  # noqa: F401  (import check)
    from .cmd import build_store, open_meta
    from .fs import FileSystem
    from .vfs import VFS

    m, fmt = open_meta(meta_url)
    m.new_session(heartbeat=12.0)
    store = build_store(fmt, None, meta=m)
    vfs = VFS(m, store, fmt=fmt)
    with _lock:
        mid = _next_mid
        _next_mid += 1
        _mounts[mid] = (FileSystem(vfs), vfs, m)
    return mid


@_wrap
def jfs_term(mid: int) -> int:
    with _lock:
        ent = _mounts.pop(mid, None)
        for key in [k for k in _files if k[0] == mid]:
            _files.pop(key)
    if ent is not None:
        ent[1].close()
        ent[2].close_session()
    return 0


@_wrap
def jfs_open(mid: int, path: str, flags: int, mode: int) -> int:
    global _next_fd
    from .fs import FSError

    try:
        f = _fs(mid).open(path, flags, mode)
    except FSError as e:
        return -e.errno
    with _lock:
        fd = _next_fd
        _next_fd += 1
        _files[(mid, fd)] = f
    return fd


@_wrap
def jfs_close(mid: int, fd: int) -> int:
    f = _files.pop((mid, fd), None)
    if f is not None:
        f.close()
    return 0


def jfs_pread(mid: int, fd: int, off: int, size: int):
    """-> bytes, or int -errno."""
    try:
        return _file(mid, fd).pread(off, size)
    except OSError as e:
        return -(e.errno or _errno.EIO)


@_wrap
def jfs_pwrite(mid: int, fd: int, off: int, data: bytes) -> int:
    return _file(mid, fd).pwrite(off, data)


@_wrap
def jfs_flush(mid: int, fd: int) -> int:
    _file(mid, fd).flush()
    return 0


@_wrap
def jfs_mkdir(mid: int, path: str, mode: int) -> int:
    _fs(mid).mkdir(path, mode)


@_wrap
def jfs_rmdir(mid: int, path: str) -> int:
    _fs(mid).rmdir(path)


@_wrap
def jfs_unlink(mid: int, path: str) -> int:
    _fs(mid).unlink(path)


@_wrap
def jfs_rename(mid: int, src: str, dst: str) -> int:
    _fs(mid).rename(src, dst)


@_wrap
def jfs_truncate(mid: int, path: str, length: int) -> int:
    _fs(mid).truncate(path, length)


def jfs_stat(mid: int, path: str):
    """-> (size, mode_with_type, uid, gid, atime, mtime, ctime, nlink)
    or int -errno."""
    from .fs import FSError
    from .meta.types import type_to_stat_mode

    try:
        a = _fs(mid).stat(path)
    except FSError as e:
        return -e.errno
    except OSError as e:
        return -(e.errno or _errno.EIO)
    return (a.length, type_to_stat_mode(a.typ, a.mode), a.uid, a.gid,
            a.atime, a.mtime, a.ctime, a.nlink)


def jfs_listdir(mid: int, path: str):
    """-> newline-joined names string, or int -errno."""
    from .fs import FSError

    try:
        entries = _fs(mid).listdir(path)
    except FSError as e:
        return -e.errno
    except OSError as e:
        return -(e.errno or _errno.EIO)
    return "\n".join(
        e.name.decode("utf-8", "replace")
        for e in entries
        if e.name not in (b".", b"..")
    )


def jfs_statvfs(mid: int):
    """-> (total_bytes, avail_bytes, used_inodes, avail_inodes) or -errno."""
    try:
        return tuple(_fs(mid).statfs())
    except OSError as e:
        return -(e.errno or _errno.EIO)
