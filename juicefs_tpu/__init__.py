"""juicefs_tpu — a TPU-native distributed POSIX file system.

Capability target: the JuiceFS architecture (see SURVEY.md) — a pluggable
transactional metadata engine plus an object-storage data plane that splits
files into 64 MiB chunks / write-once slices / 4 MiB blocks — with the block
data plane (content hashing, compression, content-addressed dedup scanning)
running as batched JAX kernels on TPU behind the chunk-store boundary.

Layer map (mirrors reference layers, SURVEY.md §1):

    cmd/      CLI driver (format, mount, bench, gc, fsck, sync, ...)
    fuse/     kernel adapter (FUSE protocol server)
    vfs/      VFS core: handles, DataReader, DataWriter, control files
    meta/     metadata engine: Meta interface, BaseMeta, TKV engines
    chunk/    chunk store: pages, block cache, write pipeline
    object/   object storage abstraction + wrappers
    compress/ block compressors (none / lz4 / zstd)
    tpu/      TPU data plane: JTH-256 hashing, dedup scan, sharded pipelines
    utils/    logging, codecs, small shared helpers
"""

__version__ = "0.1.0"
