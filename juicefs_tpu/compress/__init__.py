"""Block compressors (reference: pkg/compress/compress.go:31-49).

The reference binds C libzstd / liblz4 through cgo; here the same native
libraries are bound directly:
  - LZ4 block format via ctypes -> system liblz4 (reference compress.go:107-120)
  - Zstd level 1 via the libzstd-backed `zstandard` module (compress.go:71-105)

Contract matches the reference Compressor interface:
  compress_bound(n) -> worst-case output size
  compress(data) -> bytes
  decompress(data, dst_size) -> bytes  (dst_size = exact original size,
  known from the block key's size field, as in the reference read path)
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading
from typing import Optional

__all__ = ["Compressor", "new_compressor", "NoneCompressor", "LZ4Compressor", "ZstdCompressor"]


class Compressor:
    name = "none"

    def compress_bound(self, n: int) -> int:
        raise NotImplementedError

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        raise NotImplementedError


class NoneCompressor(Compressor):
    name = ""

    def compress_bound(self, n: int) -> int:
        return n

    def compress(self, data: bytes) -> bytes:
        return data  # pass-through: copying every 4 MiB block costs real bandwidth

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        return data


class _LZ4Lib:
    _lib: Optional[ctypes.CDLL] = None

    @classmethod
    def get(cls) -> ctypes.CDLL:
        if cls._lib is None:
            name = ctypes.util.find_library("lz4") or "liblz4.so.1"
            lib = ctypes.CDLL(name)
            lib.LZ4_compressBound.restype = ctypes.c_int
            lib.LZ4_compressBound.argtypes = [ctypes.c_int]
            lib.LZ4_compress_default.restype = ctypes.c_int
            lib.LZ4_compress_default.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ]
            lib.LZ4_decompress_safe.restype = ctypes.c_int
            lib.LZ4_decompress_safe.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ]
            cls._lib = lib
        return cls._lib


class LZ4Compressor(Compressor):
    """LZ4 block format over system liblz4 (reference go-lz4 cgo binding)."""

    name = "lz4"

    def __init__(self):
        self._lib = _LZ4Lib.get()

    def compress_bound(self, n: int) -> int:
        return self._lib.LZ4_compressBound(n)

    def compress(self, data: bytes) -> bytes:
        data = bytes(data)  # c_char_p argtype: bytes only
        bound = self.compress_bound(len(data))
        dst = ctypes.create_string_buffer(bound)
        n = self._lib.LZ4_compress_default(data, dst, len(data), bound)
        if n <= 0:
            raise IOError("lz4 compression failed")
        return dst.raw[:n]

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        data = bytes(data)
        dst = ctypes.create_string_buffer(dst_size)
        n = self._lib.LZ4_decompress_safe(data, dst, len(data), dst_size)
        if n < 0:
            raise IOError(f"lz4 decompression failed: {n}")
        return dst.raw[:n]


class ZstdCompressor(Compressor):
    """Zstd level 1 (reference compress.go:71: DataDog/zstd level 1).

    zstandard context objects wrap a single ZSTD_CCtx/DCtx and are NOT
    thread safe — concurrent compress() on one instance segfaults. The
    chunk store's upload pool and objbench both compress from worker
    threads, so contexts are per-thread here (the reference gets this for
    free: DataDog/zstd's stateless API creates a cctx per call).
    """

    name = "zstd"

    def __init__(self, level: int = 1):
        import zstandard

        self._zstd = zstandard
        self._level = level
        self._local = threading.local()

    def _ctxs(self):
        c = getattr(self._local, "c", None)
        if c is None:
            self._local.c = self._zstd.ZstdCompressor(level=self._level)
            self._local.d = self._zstd.ZstdDecompressor()
        return self._local

    def compress_bound(self, n: int) -> int:
        return n + (n >> 8) + 64

    def compress(self, data: bytes) -> bytes:
        return self._ctxs().c.compress(data)

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        return self._ctxs().d.decompress(data, max_output_size=dst_size)


def new_compressor(algo: str) -> Compressor:
    algo = (algo or "").lower()
    if algo in ("", "none"):
        return NoneCompressor()
    if algo == "lz4":
        return LZ4Compressor()
    if algo in ("zstd", "zstd1"):
        return ZstdCompressor(1)
    raise ValueError(f"unknown compress algorithm: {algo}")
