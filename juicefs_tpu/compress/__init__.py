"""Block compressors (reference: pkg/compress/compress.go:31-49).

The reference binds C libzstd / liblz4 through cgo; here the same native
libraries are bound directly:
  - LZ4 block format via ctypes -> system liblz4 (reference compress.go:107-120)
  - Zstd level 1 via the libzstd-backed `zstandard` module (compress.go:71-105)

Contract matches the reference Compressor interface:
  compress_bound(n) -> worst-case output size
  compress(data) -> bytes
  decompress(data, dst_size) -> bytes  (dst_size = exact original size,
  known from the block key's size field, as in the reference read path)
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading
from typing import Optional

__all__ = ["Compressor", "new_compressor", "NoneCompressor", "LZ4Compressor", "ZstdCompressor"]


class Compressor:
    name = "none"

    def compress_bound(self, n: int) -> int:
        raise NotImplementedError

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        raise NotImplementedError


class NoneCompressor(Compressor):
    name = ""

    def compress_bound(self, n: int) -> int:
        return n

    def compress(self, data: bytes) -> bytes:
        return data  # pass-through: copying every 4 MiB block costs real bandwidth

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        return data


class _LZ4Lib:
    _lib: Optional[ctypes.CDLL] = None

    @classmethod
    def get(cls) -> ctypes.CDLL:
        if cls._lib is None:
            name = ctypes.util.find_library("lz4") or "liblz4.so.1"
            lib = ctypes.CDLL(name)
            lib.LZ4_compressBound.restype = ctypes.c_int
            lib.LZ4_compressBound.argtypes = [ctypes.c_int]
            # void* prototypes: the call sites pass raw buffer addresses so
            # a bytearray block never pays a bytes() copy on the way in
            lib.LZ4_compress_default.restype = ctypes.c_int
            lib.LZ4_compress_default.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ]
            lib.LZ4_decompress_safe.restype = ctypes.c_int
            lib.LZ4_decompress_safe.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ]
            cls._lib = lib
        return cls._lib


def _src_buffer(data):
    """(address, length, keepalive) of a bytes-like without copying.

    bytes/readonly views pin the object itself; bytearray/writable views
    export their buffer via a ctypes array (released when the keepalive
    drops at the end of the call).
    """
    if isinstance(data, memoryview) and not data.contiguous:
        data = bytes(data)
    n = len(data)
    if n == 0:
        return None, 0, data
    if isinstance(data, bytes):
        return ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value, n, data
    try:
        arr = (ctypes.c_char * n).from_buffer(data)
    except TypeError:  # readonly view: one copy, same as the old path
        data = bytes(data)
        return ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value, n, data
    return ctypes.addressof(arr), n, arr


class LZ4Compressor(Compressor):
    """LZ4 block format over system liblz4 (reference go-lz4 cgo binding).

    The ctypes crossing is zero-copy on both sides (ISSUE 8): the old
    wrapper paid a bytes() copy of the input, a create_string_buffer
    memset of the worst-case output, and a full-buffer .raw copy before
    the slice — ~30x the cost of LZ4 itself on an incompressible 4 MiB
    block (29.6 ms vs 0.94 ms measured in-container). The destination is
    a per-thread buffer reused across calls; only the compressed `n`
    bytes are copied out. Output stays byte-identical.
    """

    name = "lz4"

    def __init__(self):
        self._lib = _LZ4Lib.get()
        self._local = threading.local()

    def compress_bound(self, n: int) -> int:
        return self._lib.LZ4_compressBound(n)

    def _dst(self, bound: int):
        buf = getattr(self._local, "buf", None)
        if buf is None or len(buf) < bound:
            buf = (ctypes.c_char * bound)()
            self._local.buf = buf
        return buf

    def compress(self, data: bytes) -> bytes:
        src, n, keep = _src_buffer(data)
        bound = self.compress_bound(n)
        dst = self._dst(bound)
        out = self._lib.LZ4_compress_default(src, ctypes.addressof(dst),
                                             n, bound)
        del keep
        if out <= 0:
            raise IOError("lz4 compression failed")
        return bytes(memoryview(dst)[:out])

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        src, n, keep = _src_buffer(data)
        dst = self._dst(dst_size)
        out = self._lib.LZ4_decompress_safe(src, ctypes.addressof(dst),
                                            n, dst_size)
        del keep
        if out < 0:
            raise IOError(f"lz4 decompression failed: {out}")
        return bytes(memoryview(dst)[:out])


class ZstdCompressor(Compressor):
    """Zstd level 1 (reference compress.go:71: DataDog/zstd level 1).

    zstandard context objects wrap a single ZSTD_CCtx/DCtx and are NOT
    thread safe — concurrent compress() on one instance segfaults. The
    chunk store's upload pool and objbench both compress from worker
    threads, so contexts are per-thread here (the reference gets this for
    free: DataDog/zstd's stateless API creates a cctx per call).
    """

    name = "zstd"

    def __init__(self, level: int = 1):
        import zstandard

        self._zstd = zstandard
        self._level = level
        self._local = threading.local()

    def _ctxs(self):
        c = getattr(self._local, "c", None)
        if c is None:
            self._local.c = self._zstd.ZstdCompressor(level=self._level)
            self._local.d = self._zstd.ZstdDecompressor()
        return self._local

    def compress_bound(self, n: int) -> int:
        return n + (n >> 8) + 64

    def compress(self, data: bytes) -> bytes:
        return self._ctxs().c.compress(data)

    def decompress(self, data: bytes, dst_size: int) -> bytes:
        return self._ctxs().d.decompress(data, max_output_size=dst_size)


def new_compressor(algo: str) -> Compressor:
    algo = (algo or "").lower()
    if algo in ("", "none"):
        return NoneCompressor()
    if algo == "lz4":
        return LZ4Compressor()
    if algo in ("zstd", "zstd1"):
        return ZstdCompressor(1)
    raise ValueError(f"unknown compress algorithm: {algo}")
