"""Native data-plane core: ctypes bindings for libjfscore (C++).

The reference's hot data plane is native (cgo zstd/lz4, hardware CRC32C);
this package is the rebuild's equivalent. The shared library builds on
demand from jfscore.cpp with the system toolchain and is cached next to
the source; every entry point has a pure-Python fallback so the framework
degrades gracefully on hosts without a compiler.

Exports:
    crc32c(data, crc=0)            hardware CRC32C (SSE4.2 when available)
    jth256(data) -> 32B digest     C++ JTH-256, byte-identical to the spec
    jth256_batch(blocks, threads)  multithreaded batch hash
    available() -> bool
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

from ..utils import get_logger

logger = get_logger("native")

_SRC = os.path.join(os.path.dirname(__file__), "jfscore.cpp")
_SO = os.path.join(os.path.dirname(__file__), "libjfscore.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Build to a per-pid temp name and atomically rename: concurrent
    # processes may both compile, but no one ever loads a half-written .so.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build unavailable: %s", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed: %s", proc.stderr.decode()[:500])
        return False
    try:
        os.replace(tmp, _SO)
    except OSError as e:
        logger.warning("native build install failed: %s", e)
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.jfs_crc32c.restype = ctypes.c_uint32
            lib.jfs_crc32c.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
            ]
            lib.jfs_jth256.restype = None
            lib.jfs_jth256.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ]
            lib.jfs_jth256_batch.restype = None
            lib.jfs_jth256_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_size_t,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            if lib.jfs_abi_version() != 1:
                raise OSError("jfscore ABI mismatch")
            _lib = lib
        except (OSError, AttributeError) as e:
            # AttributeError: stale .so missing a symbol — fall back too.
            logger.warning("libjfscore load failed: %s", e)
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _load()
    if lib is None:
        from ..object.checksum import crc32c_py

        return crc32c_py(data, crc)
    return lib.jfs_crc32c(data, len(data), crc)


def jth256(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        from ..tpu.jth256 import jth256 as ref

        return ref(data)
    out = ctypes.create_string_buffer(32)
    lib.jfs_jth256(data, len(data), out)
    return out.raw


def jth256_batch(blocks: Sequence[bytes], threads: int = 0) -> list[bytes]:
    lib = _load()
    if lib is None:
        from ..tpu.jth256 import hash_blocks_np

        return hash_blocks_np(blocks)
    if not blocks:
        return []
    if threads <= 0:
        threads = min(len(blocks), os.cpu_count() or 1)
    n = len(blocks)
    # zero-copy pointers for bytes AND writable buffers (bytearray from
    # the WSlice block buffers — the ingest path hashes them in place;
    # the C side only reads, bounded by the explicit lengths)
    arr = (ctypes.c_char_p * n)()
    _keepalive = []
    for i, b in enumerate(blocks):
        if isinstance(b, bytes):
            arr[i] = b
        else:
            view = (ctypes.c_char * len(b)).from_buffer(b)
            _keepalive.append(view)
            arr[i] = ctypes.cast(view, ctypes.c_char_p)
    lens = (ctypes.c_size_t * n)(*[len(b) for b in blocks])
    outs = ctypes.create_string_buffer(32 * n)
    lib.jfs_jth256_batch(
        ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), lens, n, outs, threads
    )
    return [outs.raw[i * 32 : (i + 1) * 32] for i in range(n)]
