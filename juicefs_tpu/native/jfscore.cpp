// jfscore: native data-plane primitives for juicefs_tpu.
//
// The reference implements its block data plane's hot paths natively via
// cgo (C zstd/lz4, pkg/compress/compress.go:71-120; CRC32C via Go's
// hardware-accelerated hash/crc32). This library is the rebuild's
// equivalent: hardware CRC32C and the JTH-256 content hash in C++,
// exposed through a plain C ABI consumed with ctypes (and reusable from
// any language, like the reference's libjfs C ABI in sdk/java).
//
// JTH-256 here MUST stay byte-identical to the normative numpy spec in
// juicefs_tpu/tpu/jth256.py (BASELINE.md acceptance bar); the test suite
// cross-checks all implementations. Little-endian hosts assumed (x86-64,
// aarch64) — the word view and digest serialization are uint32-LE.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread jfscore.cpp -o libjfscore.so

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__x86_64__)
#include <cpuid.h>
#include <nmmintrin.h>
#endif

extern "C" {
uint32_t jfs_crc32c(const uint8_t *data, size_t n, uint32_t crc);
void jfs_jth256(const uint8_t *data, size_t n, uint8_t out[32]);
void jfs_jth256_batch(const uint8_t *const *blocks, const size_t *lens,
                      size_t count, uint8_t *outs, int threads);
int jfs_abi_version();
}

int jfs_abi_version() { return 1; }

// ---------------------------------------------------------------- CRC32C --

static uint32_t crc32c_table[8][256];
static std::atomic<bool> table_ready{false};

static void init_table() {
  if (table_ready.load(std::memory_order_acquire)) return;
  const uint32_t poly = 0x82F63B78u;  // Castagnoli, reflected
  for (int n = 0; n < 256; n++) {
    uint32_t c = (uint32_t)n;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    crc32c_table[0][n] = c;
  }
  for (int n = 0; n < 256; n++) {
    uint32_t c = crc32c_table[0][n];
    for (int k = 1; k < 8; k++) {
      c = crc32c_table[0][c & 0xFF] ^ (c >> 8);
      crc32c_table[k][n] = c;
    }
  }
  table_ready.store(true, std::memory_order_release);
}

static uint32_t crc32c_sw(const uint8_t *p, size_t n, uint32_t c) {
  init_table();
  // slicing-by-8
  while (n >= 8) {
    uint64_t word;
    memcpy(&word, p, 8);
    word ^= c;
    c = crc32c_table[7][word & 0xFF] ^ crc32c_table[6][(word >> 8) & 0xFF] ^
        crc32c_table[5][(word >> 16) & 0xFF] ^
        crc32c_table[4][(word >> 24) & 0xFF] ^
        crc32c_table[3][(word >> 32) & 0xFF] ^
        crc32c_table[2][(word >> 40) & 0xFF] ^
        crc32c_table[1][(word >> 48) & 0xFF] ^
        crc32c_table[0][(word >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n--) c = crc32c_table[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return c;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) static uint32_t crc32c_hw(const uint8_t *p,
                                                            size_t n,
                                                            uint32_t c) {
  uint64_t c64 = c;
  while (n >= 8) {
    uint64_t word;
    memcpy(&word, p, 8);
    c64 = _mm_crc32_u64(c64, word);
    p += 8;
    n -= 8;
  }
  c = (uint32_t)c64;
  while (n--) c = _mm_crc32_u8(c, *p++);
  return c;
}

static bool have_sse42() {
  unsigned a, b, c, d;
  if (!__get_cpuid(1, &a, &b, &c, &d)) return false;
  return (c & bit_SSE4_2) != 0;
}
#endif

uint32_t jfs_crc32c(const uint8_t *data, size_t n, uint32_t crc) {
  uint32_t c = crc ^ 0xFFFFFFFFu;
#if defined(__x86_64__)
  static const bool hw = have_sse42();
  c = hw ? crc32c_hw(data, n, c) : crc32c_sw(data, n, c);
#else
  c = crc32c_sw(data, n, c);
#endif
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------- JTH-256 --

static const uint32_t P1 = 0x9E3779B1u, P2 = 0x85EBCA77u, P3 = 0xC2B2AE3Du,
                      P4 = 0x27D4EB2Fu, P5 = 0x165667B1u;
static const uint32_t FM1 = 0x85EBCA6Bu, FM2 = 0xC2B2AE35u;
static const uint32_t IV[8] = {0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u,
                               0xA54FF53Au, 0x510E527Fu, 0x9B05688Cu,
                               0x1F83D9ABu, 0x5BE0CD19u};

static inline uint32_t rotl32(uint32_t x, int k) {
  return (x << k) | (x >> (32 - k));
}

// One 64 KiB lane (16384 LE words as 128x128) -> 8-word lane digest.
static void lane_compress(const uint32_t *W, uint32_t lane, uint32_t out[8]) {
  uint32_t s[128];
  const uint32_t lp3 = lane * P3;
  for (uint32_t j = 0; j < 128; j++) s[j] = P5 ^ (j * P1) ^ lp3;
  for (int r = 0; r < 128; r++) {
    const uint32_t *row = W + (size_t)r * 128;
    for (int j = 0; j < 128; j++) {  // auto-vectorizes (no cross-lane deps)
      uint32_t v = (s[j] ^ row[j]) * P1;
      v = rotl32(v, 13) * P2;
      s[j] = v ^ (v >> 15);
    }
  }
  uint32_t acc[8];
  const uint32_t lp2 = lane * P2;
  for (uint32_t k = 0; k < 8; k++) acc[k] = P4 ^ lp2 ^ (k * P1);
  for (uint32_t g = 0; g < 16; g++) {
    const uint32_t gp5 = g * P5;
    for (int k = 0; k < 8; k++) {
      uint32_t v = (acc[k] ^ s[g * 8 + k]) * P3;
      acc[k] = rotl32(v, 11) + gp5;
    }
  }
  memcpy(out, acc, 32);
}

void jfs_jth256(const uint8_t *data, size_t n, uint8_t out[32]) {
  const size_t m = n ? (n + 65535) / 65536 : 1;
  uint32_t h[8];
  memcpy(h, IV, 32);
  alignas(64) uint32_t lane_buf[16384];
  for (size_t i = 0; i < m; i++) {
    const size_t off = i * 65536;
    const size_t take = n > off ? (n - off < 65536 ? n - off : 65536) : 0;
    const uint32_t *W;
    if (take == 65536 && ((uintptr_t)(data + off) % 4 == 0)) {
      W = (const uint32_t *)(data + off);  // full aligned lane: zero-copy
    } else {
      memcpy(lane_buf, data + off, take);
      memset((uint8_t *)lane_buf + take, 0, 65536 - take);
      W = lane_buf;
    }
    uint32_t acc[8];
    lane_compress(W, (uint32_t)i, acc);
    const uint32_t ip1 = (uint32_t)i * P1;
    for (int k = 0; k < 8; k++) {
      uint32_t v = (h[k] ^ acc[k]) * P2;
      h[k] = rotl32(v, 17) + ip1;
    }
  }
  for (uint32_t k = 0; k < 8; k++) {
    uint32_t v = h[k] ^ ((uint32_t)n + k * P4);
    v ^= v >> 16;
    v *= FM1;
    v ^= v >> 13;
    v *= FM2;
    v ^= v >> 16;
    h[k] = v;
  }
  memcpy(out, h, 32);  // LE host: matches uint32-LE serialization
}

void jfs_jth256_batch(const uint8_t *const *blocks, const size_t *lens,
                      size_t count, uint8_t *outs, int threads) {
  if (threads <= 1 || count <= 1) {
    for (size_t i = 0; i < count; i++)
      jfs_jth256(blocks[i], lens[i], outs + i * 32);
    return;
  }
  std::atomic<size_t> next{0};
  auto worker = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= count) return;
      jfs_jth256(blocks[i], lens[i], outs + i * 32);
    }
  };
  unsigned nt = std::min<size_t>(threads, count);
  std::vector<std::thread> pool;
  for (unsigned t = 0; t < nt; t++) pool.emplace_back(worker);
  for (auto &t : pool) t.join();
}
