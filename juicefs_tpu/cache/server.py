"""Peer block server: serve the local block cache to cache-group peers.

A deliberately tiny read-only HTTP surface (stdlib http.server, JSON/raw
bytes — the same dependency posture as the sync cluster manager):

    GET  /block/{key}   raw cached block bytes; 404 when not cached
    HEAD /block/{key}   presence probe: size + digest headers, no body
    GET  /ring          membership/identity snapshot (debugging, and the
                        target of peer-breaker half-open probes)
    POST /warm/{key}    ring-aware warm hint (ISSUE 11): enqueue `key` on
                        THIS member's prefetch stage (PREFETCH class,
                        bounded, sheddable) so the ring owner fills its
                        own cache from the object store; 202 = accepted.
                        No request body is honored — a peer can ask this
                        node to warm a block, never to store peer bytes.

Every block response carries `X-Block-Crc32` (crc32 of the payload) so a
client can reject a wrong-block serve during membership churn — a peer
with a stale ring may be asked for a key it legitimately has, but a
corrupt or mismatched payload must never enter the reader's cache.

Serves from the DiskCache/MemCache raw tier AND from writeback staging
(`_pending_staged`): a block a peer wrote but has not uploaded yet is
exactly the block the object store cannot serve.  Peers can never write
data into each other's caches — the only mutation a peer can cause is a
warm hint, which makes this node fetch its OWN verified copy from the
object store through its bounded prefetch stage.
"""

from __future__ import annotations

import json
import socket
import threading
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Server(ThreadingHTTPServer):
    """ThreadingHTTPServer that can hard-close its live connections.
    Clients hold keep-alive sockets; a plain shutdown() only stops the
    accept loop, leaving handler threads serving those sockets — a
    stopped peer must actually go dark (tests kill it to drill the
    fall-through path, and a real unmount must not linger)."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._conns: set = set()
        self._conns_mu = threading.Lock()

    def process_request(self, request, client_address):
        with self._conns_mu:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        # normal connection teardown (miss responses send Connection:
        # close, so peers reconnect often): forget the socket, or the
        # tracking set grows one dead object per served connection
        with self._conns_mu:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        with self._conns_mu:
            conns, self._conns = list(self._conns), set()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

from ..metric import global_registry
from ..utils import get_logger

logger = get_logger("cache.server")

_reg = global_registry()
_SERVED = _reg.counter(
    "juicefs_cache_group_served",
    "Peer block requests answered from the local cache",
    ("op",),
)
_SERVED_BYTES = _reg.counter(
    "juicefs_cache_group_served_bytes",
    "Bytes served to cache-group peers from the local cache",
)
_SERVE_MISSES = _reg.counter(
    "juicefs_cache_group_serve_misses",
    "Peer block requests this node could not serve (not cached here)",
)
_WARM_REQS = _reg.counter(
    "juicefs_cache_group_warm_requests",
    "Warm hints accepted from peers (enqueued on the local prefetch "
    "stage; rejected malformed hints are not counted)",
)


class PeerBlockServer:
    """HTTP server exporting one CachedStore's block cache to the group."""

    def __init__(self, store, group: str = ""):
        self.store = store
        self.group = group
        self.addr = ""
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lookup ------------------------------------------------------------
    def _lookup(self, key: str) -> bytes | None:
        from ..chunk.cached_store import parse_block_key

        if parse_block_key(key) is None:
            return None  # only well-formed block keys; no path games
        data = self.store.cache.load(key, count_miss=False)
        if data is None:
            # spilled staged entries (past the RAM cap) re-read their file
            data = self.store._staged_lookup(key)
        return data

    def _warm(self, key: str) -> bool:
        """Ring-aware warm hint: enqueue `key` on the local prefetch
        stage.  The block size rides in the key itself (block keys are
        `{id}_{indx}_{bsize}`), so a hint can never make this node fetch
        at a size the key does not pin.  Bounded + sheddable: a flood of
        hints degrades to later demand reads, never to foreground work.

        A hint for a key THIS node's ring view does not place here is
        absorbed (202, no enqueue): during membership churn two members
        can each believe the other owns a key, and enqueueing it would
        make `_prefetch_block` forward the hint straight back — a
        self-sustaining ping-pong per key for as long as the views
        diverge."""
        from ..chunk.cached_store import parse_block_key

        parsed = parse_block_key(key)
        if parsed is None or parsed[2] <= 0:
            return False  # only well-formed block keys; no path games
        try:
            group = getattr(self.store, "cache_group", None)
            if group is not None and not group.owns(key):
                return True  # stale-ring hint: absorb, never bounce it back
            _WARM_REQS.inc()
            self.store.prefetcher.fetch((key, parsed[2]))
            return True
        except Exception as e:
            # a hint is advisory: an internal error must neither kill the
            # handler thread nor desync the keep-alive socket — answer
            # 400 (the sender's breaker sees a sick peer) and move on
            logger.warning("warm hint %s degraded: %s", key, e)
            return False

    def ring_view(self) -> dict:
        group = getattr(self.store, "cache_group", None)
        view = {"group": self.group, "addr": self.addr}
        if group is not None:
            view.update(ring_size=len(group.ring),
                        members=group.ring.members)
        return view

    # -- lifecycle ---------------------------------------------------------
    def start(self, listen: str = "127.0.0.1:0") -> str:
        """Bind + serve on a daemon thread; returns the bound host:port
        (port 0 auto-picks, the address peers will dial)."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _block(self, send_body: bool) -> None:
                key = self.path[len("/block/"):].split("?", 1)[0]
                data = server._lookup(key)
                if data is None:
                    _SERVE_MISSES.inc()
                    self.send_error(404)
                    return
                data = bytes(data)
                _SERVED.labels("get" if send_body else "head").inc()
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Block-Crc32", str(zlib.crc32(data)))
                # echo the key the server RESOLVED: the client rejects a
                # mismatched echo (routing mix-up = wrong-block serve)
                self.send_header("X-Block-Key", key)
                self.end_headers()
                if send_body:
                    _SERVED_BYTES.inc(len(data))
                    self.wfile.write(data)

            def _json(self, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path.startswith("/block/"):
                    self._block(send_body=True)
                elif self.path.split("?", 1)[0] == "/ring":
                    self._json(server.ring_view())
                else:
                    self.send_error(404)

            def do_HEAD(self):  # noqa: N802
                if self.path.startswith("/block/"):
                    self._block(send_body=False)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                # drain any body first: a keep-alive connection with an
                # unread body would desync the next request on the socket
                try:
                    ln = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    self.send_error(400)
                    self.close_connection = True
                    return
                if ln > 1 << 20:
                    # oversized body: a partial drain would desync the
                    # socket — refuse and drop the connection instead
                    self.send_error(413)
                    self.close_connection = True
                    return
                if ln:
                    self.rfile.read(ln)
                if self.path.startswith("/warm/"):
                    key = self.path[len("/warm/"):].split("?", 1)[0]
                    if server._warm(key):
                        self.send_response(202)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                    else:
                        self.send_error(400)
                else:
                    self.send_error(404)

            def log_message(self, *a):
                pass

        host, _, port = listen.rpartition(":")
        self._httpd = _Server((host or "127.0.0.1", int(port or 0)), Handler)
        self.addr = (f"{self._httpd.server_address[0]}:"
                     f"{self._httpd.server_address[1]}")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"peer-cache-{self.addr}",
        )
        self._thread.start()
        logger.info("cache-group %r peer server on %s", self.group, self.addr)
        return self.addr

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.close_all_connections()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
