"""Consistent-hash ring with bounded virtual nodes.

Placement substrate for the cache group: each block key maps to one
owner peer, joins/leaves move only ~1/n of the keyspace, and weights
skew ownership toward bigger caches.  Virtual nodes smooth the
partition; the TOTAL vnode count is bounded so a large fleet cannot
make ring rebuilds (every heartbeat) quadratic.

Deterministic by construction — every member hashes the same membership
to the same ring, so owners agree without talking to each other (stale
membership windows are healed by the digest check on peer responses and
the object-store fallthrough, never by coordination).
"""

from __future__ import annotations

import bisect
import hashlib

DEFAULT_VNODES = 64
MAX_TOTAL_VNODES = 4096


def _hash(data: str) -> int:
    # md5 for spread (crc32 clusters badly on short similar keys); the
    # first 8 bytes are plenty of ring resolution
    return int.from_bytes(hashlib.md5(data.encode()).digest()[:8], "big")


class HashRing:
    """Immutable-after-rebuild consistent-hash ring.

    `rebuild({node: weight})` replaces the membership wholesale (the
    discovery loop always has the full view — incremental add/remove
    would just re-implement rebuild with more states to get wrong).
    """

    def __init__(self, vnodes: int = DEFAULT_VNODES,
                 max_total: int = MAX_TOTAL_VNODES):
        self.vnodes = max(1, vnodes)
        self.max_total = max_total
        # (points, owners, members) swapped as ONE tuple: readers run
        # unlocked on the read hot path, so a rebuild must never expose a
        # torn view (new points against old owners -> IndexError)
        self._state: tuple[list[int], list[str], dict[str, int]] = \
            ([], [], {})

    @property
    def _points(self) -> list[int]:
        return self._state[0]

    @property
    def _owners(self) -> list[str]:
        return self._state[1]

    @property
    def members(self) -> dict[str, int]:
        return dict(self._state[2])

    def __len__(self) -> int:
        return len(self._state[2])

    def rebuild(self, nodes: dict[str, int]) -> None:
        nodes = {n: max(1, int(w)) for n, w in nodes.items() if n}
        total_weight = sum(nodes.values())
        per_unit = self.vnodes
        if total_weight * per_unit > self.max_total:
            # bounded: scale everyone down proportionally, floor 1
            per_unit = max(1, self.max_total // max(total_weight, 1))
        points: list[tuple[int, str]] = []
        for node, weight in nodes.items():
            for i in range(per_unit * weight):
                points.append((_hash(f"{node}#{i}"), node))
        points.sort()
        self._state = ([p for p, _ in points], [n for _, n in points], nodes)

    def owner(self, key: str) -> str | None:
        """The peer owning `key`, or None on an empty ring."""
        points, owners, _ = self._state
        if not points:
            return None
        i = bisect.bisect_right(points, _hash(key))
        if i == len(points):
            i = 0
        return owners[i]

    def owners(self, key: str, n: int = 1) -> list[str]:
        """Up to `n` DISTINCT peers for `key`, walking clockwise from the
        owner (replica/fallback order)."""
        points, owners, members = self._state
        if not points or n <= 0:
            return []
        out: list[str] = []
        i = bisect.bisect_right(points, _hash(key))
        for step in range(len(points)):
            node = owners[(i + step) % len(points)]
            if node not in out:
                out.append(node)
                if len(out) >= min(n, len(members)):
                    break
        return out
