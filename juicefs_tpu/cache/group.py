"""CacheGroup client: place block keys on the ring, read from peers.

The read path's new rung (chunk/cached_store.py `_load_block`):

    local cache -> staging -> OWNER PEER -> object store (or EIO rung)

Peer reads deliberately BYPASS the object backend's breaker: the whole
point of the tier is that it keeps serving while the backend browns out,
so a peer GET is gated only by that peer's OWN breaker.  Failure
contract (ISSUE 4): a dead/slow/refusing peer is a TRANSIENT event —
counted, breaker-recorded, fallen through — and a digest-mismatched
payload (membership churn serving the wrong bytes) is rejected before it
can enter the local cache.  The group may degrade, never fail a read.

Membership: `refresh()` rebuilds the ring from the meta engine's live
sessions (the ones publishing a matching `cache_group` + `peer_addr` in
their session info), honoring `group_weight` and skipping sessions whose
heartbeat already expired.  Static peer lists serve tests and fixed
fleets.  Refresh is time-gated on the read path (heartbeat cadence), so
a busy reader pays one session scan per interval, not per miss.
"""

from __future__ import annotations

import http.client
import threading
import time
import zlib
from typing import Optional

from ..metric import global_registry
from ..metric.trace import global_tracer, stage_hist
from ..object.resilient import CircuitBreaker
from ..utils import get_logger
from .ring import HashRing

logger = get_logger("cache.group")

_TR = global_tracer()
_H_PEER = stage_hist("cache", "peer_get")

_reg = global_registry()
_HITS = _reg.counter(
    "juicefs_cache_group_peer_hits",
    "Block reads served by a cache-group peer (no object-store GET)",
)
_MISSES = _reg.counter(
    "juicefs_cache_group_peer_misses",
    "Peer lookups that found no usable copy (fell through to the backend)",
)
_ERRORS = _reg.counter(
    "juicefs_cache_group_peer_errors",
    "Peer fetch failures by class (transient=dead/slow peer, "
    "digest=wrong-block or corrupt payload)",
    ("class",),
)
_RING_SIZE = _reg.gauge(
    "juicefs_cache_group_ring_size",
    "Live members of the cache-group ring",
    ("group",),
)
_PEER_HIST = _reg.histogram(
    "juicefs_cache_group_peer_get_seconds",
    "Peer block GET latency (successful fetches)",
    ("group",),
)
_WARM_HINTS = _reg.counter(
    "juicefs_cache_group_warm_hints",
    "Warm hints sent to ring owners (a non-owned block's prefetch "
    "delegated instead of a redundant local object GET)",
)


class GroupPeer:
    """One remote member: its address plus its own circuit breaker (a
    flapping peer is isolated without touching the others or the
    backend's breaker)."""

    def __init__(self, addr: str, probe_interval: float = 1.0,
                 timeout: float = 2.0):
        self.addr = addr
        self.timeout = timeout
        # per-thread keep-alive connections (the server speaks HTTP/1.1):
        # a reader streaming a file owned by one peer must not pay a TCP
        # handshake per block.  http.client auto-reconnects a connection
        # whose socket the server closed (sock reset to None).
        self._local = threading.local()
        self.breaker = CircuitBreaker(
            backend=f"peer:{addr}", threshold=0.5, min_samples=4,
            probe_interval=probe_interval, probe=self._probe,
            window=15.0,
        )

    def _split(self) -> tuple[str, int]:
        host, _, port = self.addr.rpartition(":")
        return host or "127.0.0.1", int(port)

    def _probe(self) -> bool:
        """Half-open probe: any /ring response means the peer is back."""
        try:
            host, port = self._split()
            conn = http.client.HTTPConnection(host, port, timeout=1.0)
            try:
                conn.request("GET", "/ring")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except Exception:
            return False

    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            host, port = self._split()
            conn = http.client.HTTPConnection(host, port,
                                              timeout=self.timeout)
            self._local.conn = conn
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:
                pass

    def get_block(self, key: str) -> Optional[bytes]:
        """Fetch one block; None = clean miss (peer answered 404).
        Anything else non-200, a short body, or a digest mismatch raises."""
        resp = body = None
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request("GET", "/block/" + key)
                resp = conn.getresponse()
                body = resp.read()
                break
            except (http.client.CannotSendRequest, http.client.BadStatusLine,
                    BrokenPipeError, ConnectionResetError):
                # stale keep-alive socket (peer idled us out): one clean
                # retry on a fresh connection, then it IS a peer failure
                self._drop_connection()
                if attempt:
                    raise
            except Exception:
                self._drop_connection()
                raise
        # body fully read: the keep-alive connection stays usable either
        # way, so no close here — the next block reuses it
        if resp.status == 404:
            return None
        if resp.status != 200:
            raise IOError(f"peer {self.addr}: HTTP {resp.status}")
        want = resp.getheader("X-Block-Crc32")
        if want is None or int(want) != zlib.crc32(body):
            raise _DigestMismatch(
                f"peer {self.addr}: digest mismatch for {key}"
            )
        echoed = resp.getheader("X-Block-Key")
        if echoed is not None and echoed != key:
            raise _DigestMismatch(
                f"peer {self.addr}: served {echoed!r} for {key!r}"
            )
        return body

    def warm(self, key: str) -> bool:
        """Ask this peer to warm `key` into ITS cache (no bytes move to
        the caller).  The peer routes the hint through its own PREFETCH
        stage, so it is bounded and sheddable there; 202 = accepted."""
        resp = None
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request("POST", "/warm/" + key,
                             headers={"Content-Length": "0"})
                resp = conn.getresponse()
                resp.read()  # drain: keep the keep-alive socket usable
                break
            except (http.client.CannotSendRequest, http.client.BadStatusLine,
                    BrokenPipeError, ConnectionResetError):
                self._drop_connection()
                if attempt:
                    raise
            except Exception:
                self._drop_connection()
                raise
        return resp.status in (200, 202)

    def close(self) -> None:
        self._drop_connection()
        self.breaker.close()


class _DigestMismatch(IOError):
    """Peer answered with the wrong bytes (stale ring / corrupt copy)."""


class CacheGroup:
    """Ring + peer set + fetch policy for one named cache group."""

    def __init__(self, name: str, self_addr: str = "", meta=None,
                 weight: int = 1, static_peers: Optional[dict[str, int]] = None,
                 refresh_interval: float = 5.0, peer_timeout: float = 2.0,
                 fallback_peers: int = 2, vnodes: int = 64):
        self.name = name
        self.self_addr = self_addr
        self.meta = meta
        self.weight = max(1, weight)
        self.peer_timeout = peer_timeout
        self.fallback_peers = max(1, fallback_peers)
        self.refresh_interval = refresh_interval
        self.ring = HashRing(vnodes=vnodes)
        self._peers: dict[str, GroupPeer] = {}
        self._static = dict(static_peers or {})
        self._mu = threading.Lock()
        self._last_refresh = 0.0
        self._closed = False
        self.refresh()

    # -- membership --------------------------------------------------------
    def _discover(self) -> dict[str, int]:
        """addr -> weight of every live serving member (self included)."""
        members = dict(self._static)
        if self.self_addr:
            members.setdefault(self.self_addr, self.weight)
        if self.meta is not None:
            now = time.time()
            try:
                sessions = self.meta.do_list_sessions()
            except Exception as e:
                logger.warning("cache-group discovery failed: %s", e)
                return members
            for s in sessions:
                if getattr(s, "cache_group", "") != self.name:
                    continue
                addr = getattr(s, "peer_addr", "")
                if not addr:
                    continue  # client-only member: consults, never serves
                expire = getattr(s, "expire", 0.0) or 0.0
                if 0 < expire < now:
                    continue  # heartbeat already stale: reaped from the ring
                members[addr] = max(1, int(getattr(s, "group_weight", 1)))
        return members

    def refresh(self, force: bool = False) -> None:
        """Rebuild the ring from current membership (time-gated unless
        forced); drops vanished peers and closes their breakers."""
        now = time.monotonic()
        with self._mu:
            if self._closed:
                return
            if not force and now - self._last_refresh < self.refresh_interval:
                return
            self._last_refresh = now
        members = self._discover()
        with self._mu:
            if self._closed:
                return
            self.ring.rebuild(members)
            for addr in members:
                if addr != self.self_addr and addr not in self._peers:
                    self._peers[addr] = GroupPeer(
                        addr, timeout=self.peer_timeout)
            for addr in list(self._peers):
                if addr not in members:
                    self._peers.pop(addr).close()
            _RING_SIZE.labels(self.name).set(len(self.ring))

    def owns(self, key: str) -> bool:
        """True when this member is the ring owner of `key` (empty ring:
        everyone owns everything — warmup degrades to fill-all)."""
        owner = self.ring.owner(key)
        return owner is None or owner == self.self_addr

    # -- the read rung ------------------------------------------------------
    def fetch(self, key: str, bsize: int, parent=None) -> Optional[bytes]:
        """Try the owner peer (then ring fallbacks) for one block.
        Returns the verified bytes, or None to fall through to the object
        store.  NEVER raises — a cache group degrades, it does not fail."""
        try:
            return self._fetch(key, bsize, parent)
        except Exception:
            # the never-fail contract is load-bearing (this sits on the
            # read hot path): anything unexpected degrades to the backend
            logger.exception("cache-group fetch %s degraded", key)
            _ERRORS.labels("transient").inc()
            return None

    def _fetch(self, key: str, bsize: int, parent=None) -> Optional[bytes]:
        self.refresh()
        order = self.ring.owners(key, self.fallback_peers)
        tried = False
        with _TR.span("cache", "peer_get", hist=_H_PEER, parent=parent) as sp:
            if sp.active:
                sp.set(key=key, bytes=bsize)
            for addr in order:
                if addr == self.self_addr:
                    continue  # local tiers were already consulted
                with self._mu:
                    peer = self._peers.get(addr)
                if peer is None or not peer.breaker.allow():
                    continue
                tried = True
                t0 = time.perf_counter()
                try:
                    data = peer.get_block(key)
                except _DigestMismatch as e:
                    _ERRORS.labels("digest").inc()
                    peer.breaker.record_failure()
                    logger.warning("%s", e)
                    continue
                except Exception as e:
                    _ERRORS.labels("transient").inc()
                    peer.breaker.record_failure()
                    logger.warning("peer %s GET %s: %s", addr, key, e)
                    continue
                if data is not None and len(data) != bsize:
                    # a well-formed response for a DIFFERENT block size:
                    # stale ring somewhere — same failure class as a
                    # digest mismatch, including for the breaker (a peer
                    # consistently serving wrong blocks must trip it)
                    _ERRORS.labels("digest").inc()
                    peer.breaker.record_failure()
                    continue
                peer.breaker.record_success()
                if data is None:
                    continue  # clean 404: healthy peer, no copy
                _PEER_HIST.labels(self.name).observe(
                    time.perf_counter() - t0)
                _HITS.inc()
                if sp.active:
                    sp.set(peer=addr)
                return data
            if tried or any(a != self.self_addr for a in order):
                # a remote candidate existed (consulted, or skipped by its
                # open breaker) and yielded nothing: that is a peer miss.
                # A self-only ring consults nobody — counting those reads
                # as misses would show a fake 0% hit rate during rollout.
                _MISSES.inc()
        return None

    # -- ring-aware warm placement (ISSUE 11) -------------------------------
    def warm(self, key: str) -> bool:
        """Hint the ring owner of `key` to warm it into ITS cache.  Used
        by the prefetch stage for non-owned blocks: the owner pays the
        one object GET for the whole group and later reads take the peer
        rung.  No size travels with the hint — block keys pin their own
        bsize and the owner re-derives it.  Fire-and-forget semantics —
        NEVER raises, never moves bytes to this member; False = no owner
        reachable (the block will simply warm on demand)."""
        try:
            self.refresh()
            owner = self.ring.owner(key)
            if owner is None or owner == self.self_addr:
                return False  # empty ring / self-owned: nothing to hint
            with self._mu:
                peer = self._peers.get(owner)
            if peer is None or not peer.breaker.allow():
                return False
            try:
                ok = peer.warm(key)
            except Exception as e:
                _ERRORS.labels("transient").inc()
                peer.breaker.record_failure()
                logger.warning("peer %s warm %s: %s", owner, key, e)
                return False
            if not ok:
                # the peer answered but refused (5xx/400): that is a sick
                # peer for the warm path — the breaker must see it, or a
                # permanently erroring owner eats one HTTP RTT per
                # non-owned prefetch forever
                _ERRORS.labels("transient").inc()
                peer.breaker.record_failure()
                return False
            peer.breaker.record_success()
            _WARM_HINTS.inc()
            return True
        except Exception:
            logger.exception("cache-group warm %s degraded", key)
            return False

    # -- observability ------------------------------------------------------
    def health(self) -> dict:
        """Cache-group section of `.status` (vfs/internal.py)."""
        with self._mu:
            peers = {a: p.breaker.snapshot() for a, p in self._peers.items()}
        return {
            "group": self.name,
            "self": self.self_addr,
            "ring_size": len(self.ring),
            "members": self.ring.members,
            "peers": peers,
        }

    def close(self) -> None:
        with self._mu:
            self._closed = True
            peers, self._peers = list(self._peers.values()), {}
        for p in peers:
            p.close()
