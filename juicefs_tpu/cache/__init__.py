"""Cache group: peer-to-peer distributed block cache across clients
(ISSUE 4 tentpole; reference analog: JuiceFS enterprise cache groups,
the shape ML data-loading fleets use to hide object-store latency).

N training workers reading the same dataset used to issue N cold GETs
per block — every client warmed its own disk cache from the object
store.  A cache group turns the fleet's disk caches into one
consistent-hash-partitioned tier:

    read miss -> owner peer (HTTP block GET) -> local cache -> backend

Membership rides the EXISTING meta session/heartbeat machinery: a mount
serving its cache publishes (cache_group, peer_addr, group_weight) in
its session info; every member rebuilds the ring from `do_list_sessions`
on the heartbeat cadence.  No new coordination service.

A cache group may DEGRADE, never fail a read: peer errors are classified
TRANSIENT, each peer has its own circuit breaker, and every miss/error
falls through to the object store (or, while the backend breaker is
open, to the ladder's EIO rung — the peer tier is a new rung ABOVE it).
"""

from .group import CacheGroup, GroupPeer  # noqa: F401
from .ring import HashRing  # noqa: F401
from .server import PeerBlockServer  # noqa: F401
