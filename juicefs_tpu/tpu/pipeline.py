"""Streaming host->device hash pipeline (SURVEY.md §7 stage 4).

Feeds block bytes from the chunk/object layer to the device in fixed-shape
batches and returns (key, digest) pairs. Mirrors the role of the reference's
async per-block upload/load pools (pkg/chunk/cached_store.go:415-472) but as
a double-buffered device pipeline: JAX dispatch is async, so packing batch
k+1 on the host overlaps hashing batch k on the TPU; results are only
blocked on one batch behind.

Backend selection mirrors the reference's Compressor registry pattern
(pkg/compress/compress.go:31-49): "cpu" (vectorized numpy), "xla", "pallas".
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, Optional

import numpy as np

from .jth256 import BLOCK_BYTES, LANE_BYTES, digests_to_bytes, pack_blocks


@dataclass
class PipelineConfig:
    backend: str = "xla"  # cpu | xla | pallas
    batch_blocks: int = 32
    # Pad every batch to this many lanes so one compiled program serves the
    # whole stream (4 MiB default block = 64 lanes).
    pad_lanes: int = BLOCK_BYTES // LANE_BYTES


class HashPipeline:
    """hash_stream(iter[(key, bytes)]) -> iter[(key, 32-byte digest)]."""

    def __init__(self, config: Optional[PipelineConfig] = None):
        self.config = config or PipelineConfig()
        self._fn = None
        if self.config.backend != "cpu":
            try:
                import jax

                jax.devices()  # force backend init; may raise
                from .hash_jax import make_hash_fn

                self._fn = make_hash_fn(self.config.backend)
            except Exception as e:  # no usable accelerator: digests must
                # still flow, so degrade to the byte-identical CPU path.
                from ..utils import get_logger

                get_logger("tpu.pipeline").warning(
                    "backend %r unavailable (%s); falling back to cpu",
                    self.config.backend, e,
                )
                self.config.backend = "cpu"

    def hash_stream(
        self, items: Iterable[tuple[str, bytes]]
    ) -> Iterator[tuple[str, bytes]]:
        cfg = self.config
        pending: list[tuple[list[str], object]] = []
        keys: list[str] = []
        blocks: list[bytes] = []

        def dispatch():
            nonlocal keys, blocks
            if not blocks:
                return
            if self._fn is None:
                # CPU path: hash raw bytes directly (native C++ batch with
                # numpy fallback) — no packing cost, already synchronous.
                from .. import native

                pending.append((keys, native.jth256_batch(blocks)))
            else:
                words, counts, lengths = pack_blocks(blocks, pad_lanes=cfg.pad_lanes)
                pending.append((keys, self._fn(words, counts, lengths)))
            keys, blocks = [], []

        def drain(batch) -> Iterator[tuple[str, bytes]]:
            bkeys, out = batch
            digests = out if isinstance(out, list) else digests_to_bytes(np.asarray(out))
            return zip(bkeys, digests[: len(bkeys)])

        for key, data in items:
            if len(data) > cfg.pad_lanes * LANE_BYTES:
                raise ValueError(f"block {key} larger than pipeline pad size")
            keys.append(key)
            blocks.append(data)
            if len(blocks) >= cfg.batch_blocks:
                dispatch()
                # Keep exactly one batch in flight: async dispatch means the
                # device hashes batch k while the host packs batch k+1.
                while len(pending) > 1:
                    yield from drain(pending.pop(0))
        dispatch()
        while pending:
            yield from drain(pending.pop(0))

    def hash_blocks(self, blocks: Iterable[bytes]) -> list[bytes]:
        return [d for _, d in self.hash_stream((str(i), b) for i, b in enumerate(blocks))]
