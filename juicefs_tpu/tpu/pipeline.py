"""Streaming host->device hash pipeline (SURVEY.md §7 stage 4).

Feeds block bytes from the chunk/object layer to the device in fixed-shape
batches and returns (key, digest) pairs. Mirrors the role of the reference's
async per-block upload/load pools (pkg/chunk/cached_store.go:415-472) but as
a double-buffered device pipeline: JAX dispatch is async, so packing batch
k+1 on the host overlaps hashing batch k on the TPU; results are only
blocked on one batch behind.

Backend selection mirrors the reference's Compressor registry pattern
(pkg/compress/compress.go:31-49): "cpu" (vectorized numpy), "xla", "pallas".
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterable, Iterator, Optional

import numpy as np

from ..metric import global_registry
from ..metric.trace import global_tracer, stage_hist
from .jth256 import (
    BLOCK_BYTES,
    LANE_BYTES,
    digests_to_bytes,
    hash_packed_np,
    pack_blocks,
)

_reg = global_registry()
_BLOCKS_HASHED = _reg.counter(
    "juicefs_tpu_blocks_hashed", "Blocks hashed by the TPU pipeline"
)
_HASH_BYTES = _reg.counter(
    "juicefs_tpu_hash_bytes", "Raw bytes hashed by the TPU pipeline"
)
_H2D_BYTES = _reg.counter(
    "juicefs_tpu_h2d_bytes",
    "Host-to-device bytes shipped as packed hash batches",
)
_BATCH_BLOCKS = _reg.histogram(
    "juicefs_tpu_batch_blocks", "Blocks per dispatched hash batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
)
_TR = global_tracer()
_H_DISPATCH = stage_hist("tpu", "hash", "dispatch")
_H_DRAIN = stage_hist("tpu", "hash", "drain")


@dataclass
class PipelineConfig:
    backend: str = "xla"  # cpu | xla | pallas
    batch_blocks: int = 32
    # Pad every batch to this many lanes so one compiled program serves the
    # whole stream (4 MiB default block = 64 lanes).
    pad_lanes: int = BLOCK_BYTES // LANE_BYTES
    # Dispatched-but-undrained batches allowed before hash_stream blocks on
    # the oldest result.  2 = classic double buffering (device hashes batch
    # k while the host packs k+1); deeper keeps the device busy across a
    # fetch hiccup upstream at the cost of one packed batch of host RAM per
    # extra slot.
    max_inflight_batches: int = 2


class HashPipeline:
    """hash_stream(iter[(key, bytes)]) -> iter[(key, 32-byte digest)]."""

    def __init__(self, config: Optional[PipelineConfig] = None):
        self.config = config or PipelineConfig()
        self._fn = None
        self._plane = None
        if self.config.backend != "cpu":
            try:
                import jax

                jax.devices()  # force backend init; may raise
                if self.config.backend == "xla":
                    # xla rides the sharding plane (ISSUE 20): mesh over
                    # all local devices, single-device jit on the degrade
                    # rung — byte-identical either way.
                    from .sharding import get_plane

                    self._plane = get_plane()
                    self._fn = self._plane.hash_async
                else:
                    from .hash_jax import make_hash_fn

                    self._fn = make_hash_fn(self.config.backend)
            except Exception as e:  # no usable accelerator: digests must
                # still flow, so degrade to the byte-identical CPU path.
                from ..utils import get_logger

                get_logger("tpu.pipeline").warning(
                    "backend %r unavailable (%s); falling back to cpu",
                    self.config.backend, e,
                )
                self.config.backend = "cpu"
                self._plane = None

    def hash_stream(
        self, items: Iterable[tuple[str, bytes]]
    ) -> Iterator[tuple[str, bytes]]:
        cfg = self.config
        pending: list[tuple[list[str], object]] = []
        keys: list[str] = []
        blocks: list[bytes] = []

        def dispatch():
            nonlocal keys, blocks
            if not blocks:
                return
            nbytes = sum(len(b) for b in blocks)
            with _TR.span("tpu", "hash", stage="dispatch",
                          hist=_H_DISPATCH) as sp:
                if sp.active:
                    sp.set(batch=len(blocks), bytes=nbytes,
                           backend=self.config.backend)
                if self._fn is None:
                    # CPU path: hash raw bytes directly (native C++ batch with
                    # numpy fallback) — no packing cost, already synchronous,
                    # and no device transfer (h2d counter stays untouched).
                    from .. import native

                    pending.append((keys, native.jth256_batch(blocks)))
                else:
                    words, counts, lengths = pack_blocks(blocks, pad_lanes=cfg.pad_lanes)
                    _H2D_BYTES.inc(words.nbytes)
                    pending.append((keys, self._fn(words, counts, lengths)))
            _BATCH_BLOCKS.observe(len(blocks))
            _BLOCKS_HASHED.inc(len(blocks))
            _HASH_BYTES.inc(nbytes)
            keys, blocks = [], []

        def drain(batch) -> Iterator[tuple[str, bytes]]:
            bkeys, out = batch
            if isinstance(out, list):
                digests = out
            else:
                # blocking device sync: the stage where dispatch latency
                # actually lands (JAX dispatch above is async)
                with _TR.span("tpu", "hash", stage="drain",
                              hist=_H_DRAIN) as sp:
                    if sp.active:
                        sp.set(batch=len(bkeys),
                               backend=self.config.backend)
                    digests = digests_to_bytes(np.asarray(out))
            return zip(bkeys, digests[: len(bkeys)])

        for key, data in items:
            if len(data) > cfg.pad_lanes * LANE_BYTES:
                raise ValueError(f"block {key} larger than pipeline pad size")
            keys.append(key)
            blocks.append(data)
            if len(blocks) >= cfg.batch_blocks:
                dispatch()
                # Async dispatch: the device hashes batch k while the host
                # packs later ones; block only past the configured depth.
                depth = max(1, cfg.max_inflight_batches)
                while len(pending) >= depth:
                    yield from drain(pending.pop(0))
        dispatch()
        while pending:
            yield from drain(pending.pop(0))

    def hash_blocks(self, blocks: Iterable[bytes]) -> list[bytes]:
        return [d for _, d in self.hash_stream((str(i), b) for i, b in enumerate(blocks))]

    @property
    def device_backend(self) -> bool:
        """True when digests come off an accelerator (post-degrade)."""
        return self._fn is not None

    def shard_packed(self, packed):
        """Place a packed triple on devices for the shared-H2D contract
        (ISSUE 8/20): ONE (sharded, on the plane) device transfer feeds
        both the hash and the estimator jits. This is the sharding-plane
        seam chunk/ consumers enter through — no bare device_put above
        tpu/. cpu backend: no-op (host arrays hash in numpy)."""
        if self._plane is not None:
            return self._plane.put_packed(*packed)
        if self._fn is not None:  # single-device backend (pallas)
            try:
                import jax

                return tuple(jax.device_put(a) for a in packed)
            except Exception:
                return packed
        return packed

    def shard_snapshot(self) -> dict:
        """Advisory sharding-plane stats (gc --dedup, bench output)."""
        if self._plane is not None:
            return self._plane.snapshot()
        return {
            "devices": 1 if self._fn is not None else 0,
            "mesh": None,
            "degraded": False,
            "reason": f"{self.config.backend} backend",
        }

    def hash_packed(self, words, counts, lengths,
                    n: int | None = None) -> list[bytes]:
        """Digest a pre-packed batch (shared-H2D contract, ISSUE 8): the
        caller packs once and the SAME upload feeds hash and compress
        outputs. On the cpu backend this is the vectorized numpy path —
        byte-identical, no transfer (h2d counter untouched). `n` is the
        original batch size when the input was padded by the sharding
        plane (`shard_packed`); outputs are sliced back to it."""
        if n is None:
            n = int(getattr(words, "shape", [len(counts)])[0])
        with _TR.span("tpu", "hash", stage="dispatch",
                      hist=_H_DISPATCH) as sp:
            nbytes = int(np.asarray(lengths)[:n].sum()) if n else 0
            if sp.active:
                sp.set(batch=n, bytes=nbytes,
                       backend=self.config.backend)
            if self._fn is None:
                out = hash_packed_np(words, counts, lengths)
            else:
                _H2D_BYTES.inc(words.nbytes)
                out = self._fn(words, counts, lengths)
        _BATCH_BLOCKS.observe(n)
        _BLOCKS_HASHED.inc(n)
        _HASH_BYTES.inc(nbytes)
        with _TR.span("tpu", "hash", stage="drain", hist=_H_DRAIN) as sp:
            if sp.active:
                sp.set(batch=n, backend=self.config.backend)
            return digests_to_bytes(np.asarray(out))[:n]


_FLUSH = object()  # kick(): hash whatever is buffered NOW (commit barrier)
_CLOSE = object()


class HashBatcher:
    """Bounded-queue accumulator in front of a HashPipeline (flush-timeout
    mode, ISSUE 5).

    The pipeline wants device-sized batches (batch_blocks × block_size per
    dispatch) but the ingest path produces blocks one upload at a time, and
    a writer's commit barrier (`WSlice.finish`) may be waiting on a single
    block. The batcher bridges the two rates: producers `submit()` without
    ever blocking (a full queue returns False — overload is the caller's
    degrade signal, mirroring chunk/indexer.py's drop contract), and the
    consumer pulls batches that are flushed by whichever comes first —

      - the batch filled (`batch_blocks`),
      - `flush_timeout` expired since the batch's first block (a lone
        block never waits out a full batch window), or
      - `kick()` — a commit barrier is waiting; hash what we have NOW.
    """

    def __init__(self, pipe: HashPipeline, queue_blocks: int = 64,
                 flush_timeout: float = 0.005):
        import queue as _queue

        self.pipe = pipe
        self.flush_timeout = flush_timeout
        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(1, queue_blocks))
        self._empty = _queue.Empty
        self._closed = False

    def submit(self, item) -> bool:
        """Producer side; returns False when the hash plane is saturated
        (queue full) or the batcher is closed (an item enqueued behind
        the close sentinel would never be consumed) — the caller
        degrades, it never blocks here."""
        if self._closed:
            return False
        try:
            self._q.put_nowait(item)
            return True
        except Exception:
            return False

    def kick(self) -> None:
        """Flush the current partial batch immediately. Non-blocking by
        contract (a commit barrier calls this): when the queue is full
        the marker is simply dropped — a full queue means the consumer is
        saturated and the batch flushes on size or timeout anyway."""
        try:
            self._q.put_nowait(_FLUSH)
        except Exception:
            pass

    def close(self) -> None:
        """Non-blocking by contract (ISSUE 8 satellite): the old
        blocking `put(_CLOSE)` could park the closer behind a saturated
        consumer when the queue was full. The closed flag is the
        authoritative signal — the consumer drains everything accepted
        before the flag, then exits on an empty queue; the sentinel is
        only a wake-up fast path and is dropped when there is no room."""
        self._closed = True
        try:
            self._q.put_nowait(_CLOSE)
        except Exception:
            pass

    def qsize(self) -> int:
        return self._q.qsize()

    def batches(self) -> Iterator[list]:
        """Consumer side: yield non-empty item batches until close().
        Drain guard: a close() that could not enqueue its sentinel (full
        queue) still terminates this loop — every accepted item is
        yielded first, then the closed+empty state ends it."""
        batch_blocks = max(1, self.pipe.config.batch_blocks)
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except self._empty:
                if self._closed:
                    return
                continue
            if item is _CLOSE:
                return
            if item is _FLUSH:
                continue
            batch = [item]
            deadline = time.monotonic() + self.flush_timeout
            while len(batch) < batch_blocks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except self._empty:
                    break
                if nxt is _CLOSE:
                    yield batch
                    return
                if nxt is _FLUSH:
                    break
                batch.append(nxt)
            yield batch
