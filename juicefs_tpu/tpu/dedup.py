"""Content-addressed dedup scan over digest batches.

The reference's gc classifies blocks by *name* diff only (cmd/gc.go:253-330);
dedup-by-content is the new TPU capability (BASELINE.md north star). Given a
batch of JTH-256 digests, find duplicate contents via a lexicographic
multi-key sort (jax.lax.sort with num_keys=8 maps onto XLA's sort, which TPU
executes as a bitonic network) and an adjacent-equality pass, then scatter
flags back to input order.

Output convention: for each group of equal digests, the occurrence with the
lowest original index is the *representative* (kept); the rest are marked
duplicate (reclaimable). first_idx maps every block to its representative.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def dedup_scan_jax(digests: jax.Array):
    """digests (N, 8) uint32 -> (dup_mask (N,) bool, first_idx (N,) int32).

    dup_mask[i] is True iff block i's content equals an earlier (lower
    original index) block; first_idx[i] is that representative's index
    (i itself when unique or first occurrence).
    """
    n = digests.shape[0]
    if n == 0:
        return jnp.zeros((0,), dtype=bool), jnp.zeros((0,), dtype=jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    cols = [digests[:, k] for k in range(8)]
    # Tie-break on original index so each group is ordered by appearance.
    *scols, sidx = lax.sort([*cols, idx], num_keys=9)
    sorted_d = jnp.stack(scols, axis=1)
    same_as_prev = jnp.concatenate(
        [
            jnp.zeros((1,), dtype=bool),
            jnp.all(sorted_d[1:] == sorted_d[:-1], axis=1),
        ]
    )
    # Representative (in sorted order) = last position where same_as_prev
    # was False; propagate it forward with a cummax over masked indices.
    group_start = jnp.where(same_as_prev, 0, jnp.arange(n, dtype=jnp.int32))
    group_start = lax.associative_scan(jnp.maximum, group_start)
    first_sorted = sidx[group_start]
    dup = jnp.zeros((n,), dtype=bool).at[sidx].set(same_as_prev)
    first_idx = jnp.zeros((n,), dtype=jnp.int32).at[sidx].set(first_sorted)
    return dup, first_idx


@functools.partial(jax.jit)
def scan_step_jax(words, lane_counts, lengths):
    """Full single-device scan step: hash the packed batch, dedup it.

    Returns (digests (B,8) uint32, dup_mask (B,), first_idx (B,)). This is
    the flagship jittable forward step exposed by __graft_entry__.entry().
    """
    from .hash_jax import hash_packed_jax

    digests = hash_packed_jax(words, lane_counts, lengths)
    dup, first = dedup_scan_jax(digests)
    return digests, dup, first


def dedup_digests(digests: list[bytes]):
    """Host-side helper over 32-byte digests (numpy; used by CPU backend).

    Same output convention as dedup_scan_jax.
    """
    n = len(digests)
    dup = np.zeros(n, dtype=bool)
    first = np.arange(n, dtype=np.int32)
    seen: dict[bytes, int] = {}
    for i, d in enumerate(digests):
        j = seen.setdefault(d, i)
        if j != i:
            dup[i] = True
            first[i] = j
    return dup, first
