"""JTH-256: the framework's content hash, defined TPU-first.

The reference has no content addressing at all — block keys are slice-id
based (pkg/chunk/cached_store.go:73-78) and integrity is CRC32C transfer
checksums only (pkg/object/checksum.go:28-88). JTH-256 ("JuiceFS-TPU tree
hash, 256-bit") is the new content hash powering `gc --dedup`, `fsck
--hash`, and `sync --check-new` content compare. It is designed so that one
definition runs byte-identically as

  * this numpy reference (the normative spec, and the CPU verify path), and
  * the batched jit/pallas implementations in hash_jax.py,

which is the acceptance bar set by BASELINE.md (digests must match exactly).

Design rationale (why this shape): a block is at most 4 MiB; it is zero-
padded to 64 KiB *lanes*, and each lane is viewed as a 128x128 matrix of
little-endian uint32 words — exactly one VPU-friendly (8,128)-tileable tile
stack. All mixing is uint32 mul/xor/rotate/shift (ARX + multiply), which the
TPU VPU executes natively and which wraps identically in numpy, JAX, and
Pallas. The only sequential chains are short: a 128-step row scan per lane,
a 16-step fold, and a per-block lane combine (<=64 steps) over tiny 8-word
states; everything else is embarrassingly parallel over (blocks x lanes x
128 columns), which is what lets a scan feed the MXU-era VPU at HBM rate.

Normative definition
--------------------
Constants: P1..P5 are the xxhash32 primes, FM1/FM2 the murmur3 finalizer
multipliers, IV the SHA-256 initial words. All arithmetic is mod 2^32;
rotl(x,k) rotates left.

  lane_compress(W[128][128], lane):             # W = one 64 KiB lane
      s[j]   = P5 ^ (j*P1) ^ (lane*P3)                    j in [0,128)
      repeat for r in [0,128):
          s = (s ^ W[r]) * P1
          s = rotl(s, 13) * P2
          s = s ^ (s >> 15)
      G      = s viewed as [16][8]
      acc[k] = P4 ^ (lane*P2) ^ (k*P1)                    k in [0,8)
      repeat for g in [0,16):
          acc = rotl((acc ^ G[g]) * P3, 11) + g*P5
      return acc                                          # 8 words

  jth256(data):
      n = len(data); m = max(1, ceil(n / 65536))
      pad data with zeros to m*65536 bytes; W = lanes as uint32-LE
      h = IV
      for i in [0,m): h = rotl((h ^ lane_compress(W[i], i)) * P2, 17) + i*P1
      h = h ^ (n + k*P4)                                  k in [0,8)
      h = fmix(h)    # x^=x>>16; x*=FM1; x^=x>>13; x*=FM2; x^=x>>16
      digest = h serialized uint32-LE (32 bytes)

Trailing zeros inside the final lane cannot collide with the unpadded block
because the exact byte length n is mixed before finalization; lane and word
positions are bound by the lane/j/k tweaks in every initial state.
"""

from __future__ import annotations

import binascii
from typing import Iterable, Sequence

import numpy as np

LANE_BYTES = 65536  # one lane = 64 KiB = 128x128 uint32 words
LANE_WORDS = LANE_BYTES // 4
ROWS = 128
COLS = 128
BLOCK_BYTES = 4 << 20  # default max block (pkg/chunk/cached_store.go:39-40)
MAX_LANES = BLOCK_BYTES // LANE_BYTES  # 64
DIGEST_BYTES = 32

P1 = np.uint32(0x9E3779B1)
P2 = np.uint32(0x85EBCA77)
P3 = np.uint32(0xC2B2AE3D)
P4 = np.uint32(0x27D4EB2F)
P5 = np.uint32(0x165667B1)
FM1 = np.uint32(0x85EBCA6B)
FM2 = np.uint32(0xC2B2AE35)
IV = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)

_J128 = np.arange(128, dtype=np.uint32)
_K8 = np.arange(8, dtype=np.uint32)


def _rotl(x: np.ndarray, k: int) -> np.ndarray:
    return ((x << np.uint32(k)) | (x >> np.uint32(32 - k))).astype(np.uint32)


def _fmix(x: np.ndarray) -> np.ndarray:
    x = x ^ (x >> np.uint32(16))
    x = (x * FM1).astype(np.uint32)
    x = x ^ (x >> np.uint32(13))
    x = (x * FM2).astype(np.uint32)
    return x ^ (x >> np.uint32(16))


def pack_block(data: bytes) -> np.ndarray:
    """Zero-pad one block to whole lanes -> uint32 words (m, 128, 128)."""
    n = len(data)
    m = max(1, -(-n // LANE_BYTES))
    if n > BLOCK_BYTES:
        raise ValueError(f"block larger than {BLOCK_BYTES}: {n}")
    buf = data if n == m * LANE_BYTES else data + b"\0" * (m * LANE_BYTES - n)
    return np.frombuffer(buf, dtype="<u4").reshape(m, ROWS, COLS)


def jth256(data: bytes) -> bytes:
    """Normative single-block reference (vectorized only across the lane)."""
    w = pack_block(data)
    m = w.shape[0]
    h = IV.copy()
    for lane in range(m):
        li_p1 = np.uint32((lane * 0x9E3779B1) & 0xFFFFFFFF)
        li_p2 = np.uint32((lane * 0x85EBCA77) & 0xFFFFFFFF)
        li_p3 = np.uint32((lane * 0xC2B2AE3D) & 0xFFFFFFFF)
        s = (P5 ^ (_J128 * P1) ^ li_p3).astype(np.uint32)
        for r in range(ROWS):
            s = ((s ^ w[lane, r]) * P1).astype(np.uint32)
            s = (_rotl(s, 13) * P2).astype(np.uint32)
            s = s ^ (s >> np.uint32(15))
        g = s.reshape(16, 8)
        acc = (P4 ^ li_p2 ^ (_K8 * P1)).astype(np.uint32)
        for gi in range(16):
            acc = _rotl(((acc ^ g[gi]) * P3).astype(np.uint32), 11)
            acc = (acc + np.uint32((gi * 0x165667B1) & 0xFFFFFFFF)).astype(np.uint32)
        h = _rotl(((h ^ acc) * P2).astype(np.uint32), 17)
        h = (h + li_p1).astype(np.uint32)
    h = h ^ ((np.uint32(len(data)) + _K8 * P4).astype(np.uint32))
    return _fmix(h).astype("<u4").tobytes()


def digest_hex(digest: bytes) -> str:
    return binascii.hexlify(digest).decode()


# ---------------------------------------------------------------------------
# Batched packing + vectorized numpy batch implementation (the fast CPU path
# used by --hash-backend=cpu and by the byte-identical verification tests).
# ---------------------------------------------------------------------------

def pack_blocks(
    blocks: Sequence[bytes], pad_lanes: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack a batch to fixed shape for a single compiled program.

    Returns (words (B, M, 128, 128) uint32, lane_counts (B,) int32,
    lengths (B,) uint32). Blocks shorter than M lanes are zero-padded;
    lane_counts masks the padded lanes out of the combine step, so padding
    never changes a digest.
    """
    counts = [max(1, -(-len(b) // LANE_BYTES)) for b in blocks]
    m = pad_lanes or max(counts, default=1)
    if max(counts, default=1) > m:
        raise ValueError(f"block needs {max(counts)} lanes > pad_lanes={m}")
    out = np.zeros((len(blocks), m, ROWS, COLS), dtype=np.uint32)
    for i, b in enumerate(blocks):
        w = pack_block(b)
        out[i, : w.shape[0]] = w
    lengths = np.array([len(b) for b in blocks], dtype=np.uint32)
    return out, np.array(counts, dtype=np.int32), lengths


def hash_packed_np(
    words: np.ndarray, lane_counts: np.ndarray, lengths: np.ndarray
) -> np.ndarray:
    """Vectorized batch hash: (B, M, 128, 128) -> (B, 8) uint32 digests."""
    b, m = words.shape[0], words.shape[1]
    lanes = np.arange(m, dtype=np.uint32)
    s = np.broadcast_to(
        P5 ^ (_J128 * P1)[None, None, :] ^ (lanes * P3)[None, :, None],
        (b, m, COLS),
    ).astype(np.uint32).copy()
    for r in range(ROWS):
        s = ((s ^ words[:, :, r, :]) * P1).astype(np.uint32)
        s = (_rotl(s, 13) * P2).astype(np.uint32)
        s = s ^ (s >> np.uint32(15))
    g = s.reshape(b, m, 16, 8)
    acc = np.broadcast_to(
        P4 ^ (lanes * P2)[None, :, None] ^ (_K8 * P1)[None, None, :],
        (b, m, 8),
    ).astype(np.uint32).copy()
    for gi in range(16):
        acc = _rotl(((acc ^ g[:, :, gi, :]) * P3).astype(np.uint32), 11)
        acc = (acc + np.uint32((gi * 0x165667B1) & 0xFFFFFFFF)).astype(np.uint32)
    h = np.broadcast_to(IV, (b, 8)).astype(np.uint32).copy()
    for lane in range(m):
        hn = _rotl(((h ^ acc[:, lane, :]) * P2).astype(np.uint32), 17)
        hn = (hn + np.uint32((lane * 0x9E3779B1) & 0xFFFFFFFF)).astype(np.uint32)
        live = (lane_counts > lane)[:, None]
        h = np.where(live, hn, h)
    h = h ^ ((lengths.astype(np.uint32)[:, None] + _K8[None, :] * P4).astype(np.uint32))
    return _fmix(h)


def digests_to_bytes(digests: np.ndarray) -> list[bytes]:
    """(B, 8) uint32 -> list of 32-byte digests (uint32-LE serialization)."""
    d = np.ascontiguousarray(np.asarray(digests), dtype="<u4")
    return [d[i].tobytes() for i in range(d.shape[0])]


def hash_blocks_np(blocks: Iterable[bytes]) -> list[bytes]:
    """Hash a batch of blocks on CPU (numpy). Digest-identical to jth256()."""
    blocks = list(blocks)
    if not blocks:
        return []
    words, counts, lengths = pack_blocks(blocks)
    return digests_to_bytes(hash_packed_np(words, counts, lengths))
