"""Batched compression plane (ISSUE 8 tentpole).

The third stage of the north-star triad (PAPER.md §7: device-batched
hashing + dedup scan + LZ4/Zstd compression behind the chunk-store
boundary). Hashing and the dedup scan went device-batched in PRs 3-5;
compression stayed serial ctypes-liblz4 inside each upload worker, and
BENCH_r06 showed it burning ~1.7-1.9 s of a ~2.1-2.6 s ingest.

`CompressPlane` mirrors the `HashPipeline` backend-registry contract
(`cpu | xla`, tpu/pipeline.py):

  cpu   batched encode: the batch fans out across a qos "slice"-lane
        executor sized to the host cores, one zero-copy liblz4 call per
        block (ctypes releases the GIL, so lanes compress in parallel).
  xla   the same CPU lane encode (output stays byte-identical to the
        serial ctypes path — the acceptance bar), plus a device
        compressibility estimator that rides the SAME packed H2D upload
        the HashBatcher already ships: one `pack_blocks` transfer feeds
        hash digests AND per-block entropy/ratio predictions. The
        estimate is advisory (ratio telemetry, elision-bypass inputs);
        the encoded bytes come from liblz4 either way, which is what
        makes the decompress path and every existing volume compatible.

Degrade ladder (same advisory contract as the hash plane): a backend
that fails to initialize falls back to cpu; a lane fan-out that cannot
place work (scheduler closed, queue full under `nowait`) degrades that
batch to the serial in-thread encode. Compression never fails a write
for want of parallelism — `juicefs_compress_degraded` counts every rung
taken.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional, Sequence

from ..metric import global_registry
from ..utils import get_logger

logger = get_logger("tpu.compress")

_reg = global_registry()
_BATCH_BLOCKS = _reg.histogram(
    "juicefs_compress_batch_blocks", "Blocks per batched compress call",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
)
_BYTES_IN = _reg.counter(
    "juicefs_compress_bytes_in", "Raw bytes entering the compression plane"
)
_BYTES_OUT = _reg.counter(
    "juicefs_compress_bytes_out", "Compressed bytes leaving the plane"
)
_RATIO = _reg.histogram(
    "juicefs_compress_ratio",
    "Per-block compressed/raw size ratio (1.0+ = incompressible)",
    buckets=(0.05, 0.1, 0.2, 0.3, 0.5, 0.7, 0.9, 0.97, 1.0, 1.1),
)
_DEGRADED = _reg.counter(
    "juicefs_compress_degraded",
    "Compression-plane degrades taken (device backend -> cpu at init, "
    "lane fan-out -> serial in-thread encode at runtime)",
)

BACKENDS = ("cpu", "xla")


@dataclass
class CompressBatchConfig:
    backend: str = "cpu"  # cpu | xla (xla adds the device estimator)
    # parallel encode lanes on the qos "slice" lane; 0 = host cores
    lanes: int = 0
    # batches below either floor encode serially: a lane hop costs more
    # than it saves on a lone or tiny block
    min_fanout_blocks: int = 2
    min_fanout_bytes: int = 64 << 10


def _make_estimator():
    """Jitted per-block compressibility estimator from packed words.

    Subsamples 256 bytes per 64 KiB lane (every 16th row x every 16th
    column of the uint32 word matrix), builds a per-block byte histogram
    with padded lanes masked out, and returns the byte entropy scaled to
    a predicted compressed-size fraction in (0, 1]. Runs on whatever
    backend JAX initialized; raising here is the caller's degrade signal.
    """
    import jax
    import jax.numpy as jnp

    jax.devices()  # force backend init; may raise

    @jax.jit
    def est(words, lane_counts):
        b, m = words.shape[0], words.shape[1]
        sub = words[:, :, ::16, ::16].reshape(b, -1)  # (B, M*64) uint32
        by = jnp.stack(
            [(sub >> jnp.uint32(8 * i)) & jnp.uint32(0xFF) for i in range(4)],
            axis=-1,
        ).reshape(b, -1).astype(jnp.int32)  # (B, M*256) sampled bytes
        lanes = jnp.arange(m, dtype=jnp.int32)
        mask = (lanes[None, :] < lane_counts[:, None]).astype(jnp.float32)
        w = jnp.repeat(mask, 256, axis=1)  # 256 sampled bytes per lane

        def hist(v, wt):
            return jnp.zeros((256,), jnp.float32).at[v].add(wt)

        h = jax.vmap(hist)(by, w)
        p = h / jnp.maximum(h.sum(-1, keepdims=True), 1.0)
        ent = -jnp.sum(jnp.where(p > 0, p * jnp.log2(p), 0.0), axis=-1)
        return jnp.minimum(ent / 8.0, 1.0)

    return est


class CompressPlane:
    """Batched write-path compression with the hash plane's degrade
    contract. `compress_one` is the single-block seam `_put_block`
    routes through (serial fast path, the degrade target); the ingest
    finalizer feeds whole MISS batches to `compress_blocks`."""

    def __init__(self, compressor, config: Optional[CompressBatchConfig] = None,
                 scheduler=None):
        self.compressor = compressor
        self.config = config or CompressBatchConfig()
        if self.config.backend not in BACKENDS:
            raise ValueError(
                f"unknown compress backend {self.config.backend!r} "
                f"(want {'|'.join(BACKENDS)})"
            )
        self._est_fn = None
        if self.config.backend == "xla" and self.active:
            try:
                # the estimator comes off the sharding plane (ISSUE 20):
                # pjit-sharded over the mesh when the shared pack divides,
                # the same single-device jit as before otherwise —
                # advisories identical either way
                from .sharding import get_plane

                self._est_fn = get_plane().make_estimator()
            except Exception as e:
                # no usable accelerator: compressed bytes must still flow,
                # so drop to the lane-parallel CPU plane (byte-identical
                # output; only the advisory estimate is lost)
                logger.warning(
                    "compress backend %r unavailable (%s); degrading to cpu",
                    self.config.backend, e,
                )
                self.config.backend = "cpu"
                _DEGRADED.inc()
        self._exec = None
        self.lanes = 0
        if self.active:
            from ..qos import IOClass, global_scheduler

            sched = scheduler or global_scheduler()
            self.lanes = self.config.lanes or max(2, os.cpu_count() or 2)
            # qos lane sizing: the encode fan-out shares the "slice" lane
            # (CPU-bound work, same as the read-side slice spool) at
            # INGEST class — it outranks background bulk work but never
            # starves a foreground read's slice fan-out
            self._exec = sched.executor("slice", IOClass.INGEST,
                                        width=self.lanes)
        self._lock = threading.Lock()
        # stats mirror of the global counters, per plane (bench/tests)
        self.blocks = 0
        self.batches = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.degraded = 0
        self.estimated = 0
        self.last_estimate: Optional[list] = None

    @property
    def active(self) -> bool:
        """False for the none-compressor: everything passes through."""
        return bool(self.compressor.name)

    @property
    def backend(self) -> str:
        return self.config.backend

    # -- single block (the `_put_block` seam) ------------------------------
    def compress_one(self, raw) -> bytes:
        data = self.compressor.compress(raw)
        self._account([len(raw)], [len(data)], batch=False)
        return data

    # -- whole batch (the ingest-finalizer seam) ---------------------------
    def compress_blocks(self, blocks: Sequence, packed=None) -> list[bytes]:
        """Compress a batch, fanning out across the slice lane.

        `packed` is the (words, lane_counts, lengths) triple the hash
        dispatch already uploaded (tpu/jth256.pack_blocks): with the xla
        backend it feeds the device estimator — no second H2D. Output is
        byte-identical to [compressor.compress(b) for b in blocks].
        """
        blocks = list(blocks)
        if not blocks:
            return []
        if not self.active:
            return [self.compressor.compress(b) for b in blocks]
        if self._est_fn is not None and packed is not None:
            self.estimate_packed(packed)
        nbytes = sum(len(b) for b in blocks)
        if (self._exec is None
                or len(blocks) < self.config.min_fanout_blocks
                or nbytes < self.config.min_fanout_bytes):
            out = [self.compressor.compress(b) for b in blocks]
        else:
            out = self._fanout(blocks)
        self._account([len(b) for b in blocks], [len(d) for d in out])
        return out

    def _fanout(self, blocks: list) -> list[bytes]:
        # one task per LANE, not per block: each submit/result crossing
        # is Python-level work competing for the GIL against the encode
        # threads themselves — chunking keeps the lanes C-dominated
        n = min(self.lanes, len(blocks))
        step = -(-len(blocks) // n)
        chunks = [blocks[i:i + step] for i in range(0, len(blocks), step)]
        comp = self.compressor

        def encode(chunk: list) -> list[bytes]:
            return [comp.compress(b) for b in chunk]

        futs = []
        for chunk in chunks:
            try:
                # nowait: a saturated slice lane must degrade THIS batch
                # to the serial path, not park the ingest worker behind
                # someone else's backlog (advisory contract)
                futs.append(self._exec.submit(encode, chunk, nowait=True))
            except (TimeoutError, RuntimeError):
                futs.append(None)
        out: list[bytes] = []
        degraded = 0
        for chunk, f in zip(chunks, futs):
            if f is None:
                degraded += len(chunk)
                out.extend(comp.compress(b) for b in chunk)
            else:
                out.extend(f.result())
        if degraded:
            self.degraded += degraded
            _DEGRADED.inc(degraded)
        return out

    def estimate_packed(self, packed) -> None:
        """Advisory device pass from the shared H2D words (the ingest
        worker calls this with the same packed triple the hash batch
        uploaded); failures only cost the estimate, never the batch."""
        if self._est_fn is None:
            return
        try:
            import numpy as np

            words, counts, _lengths = packed
            pred = np.asarray(self._est_fn(words, counts))
            # a plane-placed pack (ShardedPack) was padded to the mesh's
            # data-axis extent; slice the advisory back to the real batch
            n = getattr(packed, "batch", None)
            if n is not None:
                pred = pred[:n]
            with self._lock:
                self.estimated += len(pred)
                self.last_estimate = [round(float(p), 4) for p in pred]
        except Exception as e:
            logger.warning("compress estimate degraded: %s", e)
            self.degraded += 1
            _DEGRADED.inc()
            self._est_fn = None  # broken device: stop paying for retries

    def _account(self, sizes_in: list, sizes_out: list, batch=True) -> None:
        n_in, n_out = sum(sizes_in), sum(sizes_out)
        _BYTES_IN.inc(n_in)
        _BYTES_OUT.inc(n_out)
        if batch:
            _BATCH_BLOCKS.observe(len(sizes_in))
        for i, o in zip(sizes_in, sizes_out):
            if i > 0:
                _RATIO.observe(o / i)
        with self._lock:
            self.blocks += len(sizes_in)
            if batch:
                self.batches += 1
            self.bytes_in += n_in
            self.bytes_out += n_out

    def close(self) -> None:
        """Drain this plane's outstanding lane submissions (the executor
        owns only its own futures — closing never stops slice-lane
        workers another consumer shares)."""
        if self._exec is not None:
            self._exec.shutdown(wait=True, timeout=60.0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "backend": self.backend,
                "algorithm": self.compressor.name or "none",
                "lanes": self.lanes,
                "blocks": self.blocks,
                "batches": self.batches,
                "bytes_in": self.bytes_in,
                "bytes_out": self.bytes_out,
                "ratio": round(self.bytes_out / self.bytes_in, 4)
                if self.bytes_in else 0.0,
                "degraded": self.degraded,
                "estimated": self.estimated,
            }
