"""TPU data plane (the new, TPU-first capability; SURVEY.md §7 stage 4).

The reference's block data plane is CPU-side cgo (zstd/lz4 compression,
CRC32C checksums — pkg/compress/compress.go:31-49, pkg/object/checksum.go:28)
and its gc/fsck scans diff block *names* only (cmd/gc.go:253-296,
cmd/fsck.go:174-200). This package adds the north-star TPU capability:
content hashing and content-addressed dedup scanning as batched JAX/Pallas
programs, behind the chunk-store boundary, selected by --hash-backend=tpu.

Modules:
  jth256    — normative JTH-256 hash spec + numpy reference (byte-identical bar)
  hash_jax  — batched jit/pallas implementations of the same spec
  dedup     — sort-based duplicate scan over digest batches
  pipeline  — double-buffered host->device streaming hash pipeline
  sharding  — the multichip plane: (data x lane) mesh over all local
              devices, sharded placement + hash/dedup/estimator programs,
              single-device-jit degrade ladder (ISSUE 20)
"""

from .jth256 import (
    BLOCK_BYTES,
    LANE_BYTES,
    digest_hex,
    hash_blocks_np,
    jth256,
    pack_blocks,
)
from .hash_jax import hash_blocks_jax, hash_packed_jax, make_hash_fn
from .dedup import dedup_digests, dedup_scan_jax
from .pipeline import HashPipeline, PipelineConfig
from .sharding import (
    ShardedPack,
    ShardPlane,
    get_plane,
    make_mesh,
    shard_batch,
    sharded_hash_step,
    sharded_scan_step,
)

__all__ = [
    "BLOCK_BYTES",
    "LANE_BYTES",
    "jth256",
    "digest_hex",
    "pack_blocks",
    "hash_blocks_np",
    "hash_blocks_jax",
    "hash_packed_jax",
    "make_hash_fn",
    "dedup_digests",
    "dedup_scan_jax",
    "HashPipeline",
    "PipelineConfig",
    "make_mesh",
    "shard_batch",
    "sharded_hash_step",
    "sharded_scan_step",
    "ShardedPack",
    "ShardPlane",
    "get_plane",
]
