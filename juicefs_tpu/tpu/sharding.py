"""Multi-chip sharding for the scan pipeline (SURVEY.md §2.3, §7).

The reference scales its scans with goroutine pools on one host and an
ssh-launched manager/worker cluster for sync (pkg/sync/cluster.go:132,237).
The TPU-native equivalent is SPMD over a jax.sharding.Mesh with two axes:

  data — blocks of the batch (the DP analog): embarrassingly parallel,
         no communication until the final dedup, which all_gathers only
         32-byte digests (not block data) over ICI.
  lane — 64 KiB lanes *within* a block (the SP/sequence-parallel analog):
         the heavy row chains run sharded, then an all_gather of the tiny
         per-lane digests (B x M x 8 words) precedes the short sequential
         combine, which every device replays identically.

So the bytes that cross ICI are ~1/2048th of the bytes hashed; the design
follows the scaling-book recipe: annotate shardings, let XLA insert the
collectives, keep them on ICI.

ISSUE 20 promotes this module from bench helpers to the process-wide
*sharding plane* (`ShardPlane` / `get_plane()`): the single seam through
which every device consumer above tpu/ — the hash pipeline, the dedup
scan, the compress estimator, inline ingest's shared pack — places data
on devices and runs sharded programs. Degrade ladder (never an error):

  all local devices, even count >= 2   -> (data, lane) mesh, pjit-sharded
  one device / odd count / mesh-init   -> single-device jit (counted in
  failure                                 juicefs_tpu_shard_degraded)

Ragged batches pad B up to the data-axis extent by repeating the last
block (self-duplicating pad rows cannot perturb dup_mask/first_idx of
real rows); outputs are gathered replicated and sliced back, so digests,
dedup verdicts and estimator advisories are byte-identical to the
single-device plane at every batch shape.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..metric import global_registry
from ..utils import get_logger
from .dedup import dedup_scan_jax
from .hash_jax import (
    _combine_accs,
    _lane_accs,
    _lane_states,
    _row_chain_scan,
    make_hash_fn,
)

logger = get_logger("tpu.shard")

_reg = global_registry()
_DEVICES = _reg.gauge(
    "juicefs_tpu_shard_devices",
    "Devices in the sharding plane's mesh (1 = single-device jit)",
)
_H2D_BATCHES = _reg.counter(
    "juicefs_tpu_shard_h2d_batches",
    "Packed batches placed on devices by the sharding plane (ONE "
    "host->device transfer per batch feeds hash + estimator)",
)
_DEGRADED = _reg.counter(
    "juicefs_tpu_shard_degraded",
    "Sharding-plane degrades to single-device jit (odd device count, "
    "mesh-init failure, or an indivisible batch at call time)",
)


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the top-level alias (with
    check_vma) only exists on newer releases; older ones ship it under
    jax.experimental with the check_rep spelling."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:  # version window where the kwarg is still check_rep
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def make_mesh(
    n_data: int | None = None, n_lane: int = 1, devices=None
) -> Mesh:
    """Build a (data, lane) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_lane
    used = n_data * n_lane
    if used > len(devices):
        raise ValueError(f"mesh {n_data}x{n_lane} needs {used} devices, have {len(devices)}")
    arr = np.array(devices[:used]).reshape(n_data, n_lane)
    return Mesh(arr, ("data", "lane"))


def _scan_body(words, lane_counts, lengths):
    """The shared per-device scan body (used by the one-shot step and the
    fused benchmark loop — one definition, no drift): row chains on local
    lanes, gather tiny per-lane digests across the lane axis, combine,
    gather 32 B/block digests across data, dedup."""
    local_m = words.shape[1]
    loff = lax.axis_index("lane") * local_m
    s = _row_chain_scan(words, _lane_states(words, loff))
    acc = lax.all_gather(_lane_accs(s, loff), "lane", axis=1, tiled=True)
    digests = _combine_accs(acc, lane_counts, lengths)
    all_digests = lax.all_gather(digests, "data", axis=0, tiled=True)
    dup, first = dedup_scan_jax(all_digests)
    return all_digests, dup, first


def sharded_scan_step(mesh: Mesh):
    """Compile the full multi-chip scan step over `mesh`.

    Returns a jitted fn (words (B,M,128,128), lane_counts (B,), lengths (B,))
    -> (digests (B,8), dup_mask (B,), first_idx (B,)); B must divide by the
    data axis and M by the lane axis. Outputs are fully replicated.
    """

    def step(words, lane_counts, lengths):
        return _scan_body(words, lane_counts, lengths)

    mapped = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P("data", "lane", None, None), P("data"), P("data")),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(mapped)


def sharded_scan_many(mesh: Mesh):
    """Multi-iteration sharded scan as ONE device program (the honest
    benchmark form: per-dispatch relay latency amortizes away and repeated
    identical dispatches cannot be elided). Each iteration hashes a
    tweaked copy of the resident batch — the xor fuses into the first
    read — and the collectives (digest-sized only) repeat per iteration.

    Returns jit(fn(words, lane_counts, lengths, iters) -> uint32 checksum).
    """

    def many(words, lane_counts, lengths, iters):
        def body(k, acc):
            all_d, dup, _first = _scan_body(
                words ^ k.astype(jnp.uint32), lane_counts, lengths
            )
            return acc ^ all_d.sum(dtype=jnp.uint32) ^ dup.sum().astype(jnp.uint32)

        return lax.fori_loop(jnp.uint32(0), iters, body, jnp.uint32(0))

    mapped = _shard_map(
        many,
        mesh=mesh,
        in_specs=(P("data", "lane", None, None), P("data"), P("data"), P()),
        out_specs=P(),
    )
    return jax.jit(mapped)


def shard_batch(mesh: Mesh, words, lane_counts, lengths):
    """Device_put a packed batch with the scan step's input shardings.

    Ragged batches (B not divisible by the data axis — the tail of any
    real scan) are padded by repeating the LAST block: padded rows are
    valid hash inputs, and because they duplicate an earlier block they
    can only mark THEMSELVES as duplicates — dup_mask/first_idx for the
    original rows are unchanged.  Callers slice outputs back to their
    input length (`digests[:B]`, `dup[:B]`).
    """
    n_data = mesh.shape["data"]
    b = int(words.shape[0])
    pad = (-b) % n_data
    if pad:
        words = np.concatenate(
            [np.asarray(words)] + [np.asarray(words[-1:])] * pad, axis=0)
        lane_counts = np.concatenate(
            [np.asarray(lane_counts)] + [np.asarray(lane_counts[-1:])] * pad)
        lengths = np.concatenate(
            [np.asarray(lengths)] + [np.asarray(lengths[-1:])] * pad)
    ws = NamedSharding(mesh, P("data", "lane", None, None))
    bs = NamedSharding(mesh, P("data"))
    return (
        jax.device_put(words, ws),
        jax.device_put(lane_counts, bs),
        jax.device_put(lengths, bs),
    )


# ---------------------------------------------------------------------------
# The sharding plane (ISSUE 20): the one seam above which no caller touches
# jax.device_put / jax.jit directly (enforced by the tpu-shard-seam analyzer
# rule for chunk/).
# ---------------------------------------------------------------------------


class ShardedPack(tuple):
    """A packed (words, lane_counts, lengths) triple placed by the plane.

    Behaves as the plain tuple the PR 8 shared-pack contract passes
    around (``*packed`` unpacking, ``words, counts, lengths = packed``),
    but carries ``batch`` — the ORIGINAL block count before data-axis
    padding — so downstream consumers (hash metrics, estimator advisory)
    can slice gathered outputs back without re-deriving it.
    """

    def __new__(cls, arrays, batch: int):
        self = tuple.__new__(cls, arrays)
        self.batch = batch
        return self


def sharded_hash_step(mesh: Mesh):
    """Hash-only sharded step: (words, lane_counts, lengths) -> digests
    (B, 8), fully replicated. Same body as `sharded_scan_step` minus the
    dedup tail — the pipeline dedups on host against the meta index."""

    def step(words, lane_counts, lengths):
        local_m = words.shape[1]
        loff = lax.axis_index("lane") * local_m
        s = _row_chain_scan(words, _lane_states(words, loff))
        acc = lax.all_gather(_lane_accs(s, loff), "lane", axis=1, tiled=True)
        digests = _combine_accs(acc, lane_counts, lengths)
        return lax.all_gather(digests, "data", axis=0, tiled=True)

    mapped = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P("data", "lane", None, None), P("data"), P("data")),
        out_specs=P(),
    )
    return jax.jit(mapped)


def sharded_estimate_step(mesh: Mesh):
    """Sharded compressibility estimator, byte-identical to the
    single-device `compress_batch._make_estimator` math.

    Each device histograms its local lanes' sampled bytes (lane offsets
    keep the padded-lane mask global), then `psum` merges histograms over
    the lane axis. The histogram bins are integer-valued float32 counts
    (<= 16384 per bin, exactly representable), so the psum is exact in
    any order and the downstream entropy math sees bit-identical inputs.
    """

    def est(words, lane_counts):
        b, m = words.shape[0], words.shape[1]
        loff = lax.axis_index("lane") * m
        sub = words[:, :, ::16, ::16].reshape(b, -1)  # (B, m_local*64)
        by = jnp.stack(
            [(sub >> jnp.uint32(8 * i)) & jnp.uint32(0xFF) for i in range(4)],
            axis=-1,
        ).reshape(b, -1).astype(jnp.int32)
        lanes = loff + jnp.arange(m, dtype=jnp.int32)
        mask = (lanes[None, :] < lane_counts[:, None]).astype(jnp.float32)
        w = jnp.repeat(mask, 256, axis=1)  # 256 sampled bytes per lane

        def hist(v, wt):
            return jnp.zeros((256,), jnp.float32).at[v].add(wt)

        h = lax.psum(jax.vmap(hist)(by, w), "lane")
        p = h / jnp.maximum(h.sum(-1, keepdims=True), 1.0)
        ent = -jnp.sum(jnp.where(p > 0, p * jnp.log2(p), 0.0), axis=-1)
        pred = jnp.minimum(ent / 8.0, 1.0)
        return lax.all_gather(pred, "data", axis=0, tiled=True)

    mapped = _shard_map(
        est,
        mesh=mesh,
        in_specs=(P("data", "lane", None, None), P("data")),
        out_specs=P(),
    )
    return jax.jit(mapped)


class ShardPlane:
    """Process-wide multichip plane: mesh policy, sharded placement, and
    the hash/dedup/estimator programs every consumer routes through.

    Construction NEVER raises past backend init: any mesh failure lands
    on the single-device-jit rung with `juicefs_tpu_shard_degraded`
    counted (mirror of the compress plane's xla->cpu contract). Callers
    that cannot even import/init jax handle that one level up (the hash
    pipeline's cpu fallback).
    """

    def __init__(self, devices=None):
        devs = list(devices if devices is not None else jax.devices())
        self.devices = devs
        self.n_devices = max(1, len(devs))
        self.mesh: Mesh | None = None
        self.degrade_reason = ""
        self._hash_single = None  # built lazily on the degrade rung
        self._hash_sharded = None
        self._scan_sharded = None
        self._est_sharded = None
        n = len(devs)
        if n >= 2 and n % 2 == 0:
            try:
                n_lane = 2 if (n >= 4 and n % 4 == 0) else 1
                self.mesh = make_mesh(
                    n_data=n // n_lane, n_lane=n_lane, devices=devs
                )
            except Exception as e:  # mesh init failure -> single-device
                self.mesh = None
                self.degrade_reason = f"mesh init failed: {e}"
                _DEGRADED.inc()
                logger.warning(
                    "shard plane degraded to single-device jit: %s", e)
        elif n > 1:  # odd device count: no even (data, lane) factoring
            self.degrade_reason = f"odd device count {n}"
            _DEGRADED.inc()
            logger.warning(
                "shard plane degraded to single-device jit: %d devices",
                n)
        else:
            self.degrade_reason = "single device"
        _DEVICES.set(self.n_data * self.n_lane if self.mesh else 1)

    # -- mesh geometry ----------------------------------------------------
    @property
    def n_data(self) -> int:
        return self.mesh.shape["data"] if self.mesh is not None else 1

    @property
    def n_lane(self) -> int:
        return self.mesh.shape["lane"] if self.mesh is not None else 1

    def snapshot(self) -> dict:
        """Advisory stats block (gc --dedup, bench output, tests)."""
        return {
            "devices": self.n_data * self.n_lane if self.mesh else 1,
            "mesh": (
                {"data": self.n_data, "lane": self.n_lane}
                if self.mesh is not None else None
            ),
            "degraded": self.mesh is None,
            "reason": self.degrade_reason,
        }

    # -- placement --------------------------------------------------------
    def _shardable(self, words) -> bool:
        return (
            self.mesh is not None
            and words.shape[0] > 0
            and words.shape[1] % self.n_lane == 0
        )

    def put_packed(self, words, lane_counts, lengths) -> ShardedPack:
        """The ONE host->device transfer of the shared-pack contract.

        Pads B to a multiple of the data-axis extent (repeat-last-block,
        see `shard_batch`), places the triple with the scan's
        PartitionSpecs, and returns a `ShardedPack` remembering the
        original batch size. Indivisible shapes (lane axis not dividing
        M, empty batch) take the single-device placement instead —
        still exactly one transfer, still counted.
        """
        b = int(words.shape[0])
        if not self._shardable(words):
            if self.mesh is not None and b > 0:
                _DEGRADED.inc()  # sharded plane active but batch can't split
            arrays = tuple(
                jax.device_put(a) for a in (words, lane_counts, lengths))
        else:
            arrays = shard_batch(self.mesh, words, lane_counts, lengths)
        _H2D_BATCHES.inc()
        return ShardedPack(arrays, b)

    # -- programs ---------------------------------------------------------
    def hash_async(self, words, lane_counts, lengths):
        """Dispatch the hash program and return the (still-async) device
        array of gathered digests, padded length included — the streaming
        pipeline's double buffering needs dispatch to not block. Accepts
        host arrays (placed here: one counted transfer) or arrays already
        placed by `put_packed` (no second transfer)."""
        if not isinstance(words, jax.Array):
            words, lane_counts, lengths = self.put_packed(
                words, lane_counts, lengths)
        if (
            self._shardable(words)
            and int(words.shape[0]) % self.n_data == 0
        ):
            if self._hash_sharded is None:
                self._hash_sharded = sharded_hash_step(self.mesh)
            return self._hash_sharded(words, lane_counts, lengths)
        if self._hash_single is None:
            self._hash_single = make_hash_fn("xla")
        return self._hash_single(words, lane_counts, lengths)

    def hash_packed(self, words, lane_counts, lengths, n: int | None = None):
        """(B, M, 128, 128) -> (n, 8) uint32 digests, byte-identical to
        the single-device plane. `n` slices gathered outputs back past
        any data-axis padding; defaults to the input batch size."""
        if n is None:
            n = int(words.shape[0])
        if n == 0:
            return np.zeros((0, 8), dtype=np.uint32)
        out = self.hash_async(words, lane_counts, lengths)
        return np.asarray(jax.device_get(out))[:n]

    def scan_packed(self, words, lane_counts, lengths, n: int | None = None):
        """Full scan step (digests + dedup verdicts), sliced back to the
        original batch. Pad rows only ever self-duplicate, so dup/first
        for real rows match the single-device `dedup_scan_jax` exactly."""
        if n is None:
            n = int(words.shape[0])
        if n == 0:
            e = np.zeros((0,), dtype=np.int32)
            return np.zeros((0, 8), dtype=np.uint32), e.astype(bool), e
        if not isinstance(words, jax.Array):
            words, lane_counts, lengths = self.put_packed(
                words, lane_counts, lengths)
        if (
            self._shardable(words)
            and int(words.shape[0]) % self.n_data == 0
        ):
            if self._scan_sharded is None:
                self._scan_sharded = sharded_scan_step(self.mesh)
            d, dup, first = self._scan_sharded(words, lane_counts, lengths)
        else:
            digests = self.hash_packed(words, lane_counts, lengths)
            d, dup, first = digests, *dedup_scan_jax(jnp.asarray(digests))
        return (
            np.asarray(jax.device_get(d))[:n],
            np.asarray(jax.device_get(dup))[:n],
            np.asarray(jax.device_get(first))[:n],
        )

    def make_estimator(self):
        """Estimator callable for the compress plane: (words, lane_counts)
        -> predicted ratio per block. Sharded over the mesh when the
        input divides; single-device jit otherwise. Backend-init errors
        propagate — raising is the CompressPlane's degrade signal."""
        from .compress_batch import _make_estimator

        single = _make_estimator()  # may raise -> caller degrades to cpu

        def est(words, lane_counts):
            if (
                self._shardable(words)
                and int(words.shape[0]) % self.n_data == 0
            ):
                if self._est_sharded is None:
                    self._est_sharded = sharded_estimate_step(self.mesh)
                return self._est_sharded(words, lane_counts)
            return single(words, lane_counts)

        return est


_plane_lock = threading.Lock()
_plane: ShardPlane | None = None


def get_plane() -> ShardPlane:
    """The process-wide plane, built over all local devices on first use.
    Backend-init failures (no jax runtime) propagate to the caller —
    that is the hash pipeline's existing cpu-degrade signal."""
    global _plane
    with _plane_lock:
        if _plane is None:
            _plane = ShardPlane()
        return _plane


def _reset_plane_for_tests() -> None:
    global _plane
    with _plane_lock:
        _plane = None
