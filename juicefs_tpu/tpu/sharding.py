"""Multi-chip sharding for the scan pipeline (SURVEY.md §2.3, §7).

The reference scales its scans with goroutine pools on one host and an
ssh-launched manager/worker cluster for sync (pkg/sync/cluster.go:132,237).
The TPU-native equivalent is SPMD over a jax.sharding.Mesh with two axes:

  data — blocks of the batch (the DP analog): embarrassingly parallel,
         no communication until the final dedup, which all_gathers only
         32-byte digests (not block data) over ICI.
  lane — 64 KiB lanes *within* a block (the SP/sequence-parallel analog):
         the heavy row chains run sharded, then an all_gather of the tiny
         per-lane digests (B x M x 8 words) precedes the short sequential
         combine, which every device replays identically.

So the bytes that cross ICI are ~1/2048th of the bytes hashed; the design
follows the scaling-book recipe: annotate shardings, let XLA insert the
collectives, keep them on ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .dedup import dedup_scan_jax
from .hash_jax import _combine_accs, _lane_accs, _lane_states, _row_chain_scan


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the top-level alias (with
    check_vma) only exists on newer releases; older ones ship it under
    jax.experimental with the check_rep spelling."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:  # version window where the kwarg is still check_rep
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def make_mesh(
    n_data: int | None = None, n_lane: int = 1, devices=None
) -> Mesh:
    """Build a (data, lane) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_lane
    used = n_data * n_lane
    if used > len(devices):
        raise ValueError(f"mesh {n_data}x{n_lane} needs {used} devices, have {len(devices)}")
    arr = np.array(devices[:used]).reshape(n_data, n_lane)
    return Mesh(arr, ("data", "lane"))


def _scan_body(words, lane_counts, lengths):
    """The shared per-device scan body (used by the one-shot step and the
    fused benchmark loop — one definition, no drift): row chains on local
    lanes, gather tiny per-lane digests across the lane axis, combine,
    gather 32 B/block digests across data, dedup."""
    local_m = words.shape[1]
    loff = lax.axis_index("lane") * local_m
    s = _row_chain_scan(words, _lane_states(words, loff))
    acc = lax.all_gather(_lane_accs(s, loff), "lane", axis=1, tiled=True)
    digests = _combine_accs(acc, lane_counts, lengths)
    all_digests = lax.all_gather(digests, "data", axis=0, tiled=True)
    dup, first = dedup_scan_jax(all_digests)
    return all_digests, dup, first


def sharded_scan_step(mesh: Mesh):
    """Compile the full multi-chip scan step over `mesh`.

    Returns a jitted fn (words (B,M,128,128), lane_counts (B,), lengths (B,))
    -> (digests (B,8), dup_mask (B,), first_idx (B,)); B must divide by the
    data axis and M by the lane axis. Outputs are fully replicated.
    """

    def step(words, lane_counts, lengths):
        return _scan_body(words, lane_counts, lengths)

    mapped = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P("data", "lane", None, None), P("data"), P("data")),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(mapped)


def sharded_scan_many(mesh: Mesh):
    """Multi-iteration sharded scan as ONE device program (the honest
    benchmark form: per-dispatch relay latency amortizes away and repeated
    identical dispatches cannot be elided). Each iteration hashes a
    tweaked copy of the resident batch — the xor fuses into the first
    read — and the collectives (digest-sized only) repeat per iteration.

    Returns jit(fn(words, lane_counts, lengths, iters) -> uint32 checksum).
    """

    def many(words, lane_counts, lengths, iters):
        def body(k, acc):
            all_d, dup, _first = _scan_body(
                words ^ k.astype(jnp.uint32), lane_counts, lengths
            )
            return acc ^ all_d.sum(dtype=jnp.uint32) ^ dup.sum().astype(jnp.uint32)

        return lax.fori_loop(jnp.uint32(0), iters, body, jnp.uint32(0))

    mapped = _shard_map(
        many,
        mesh=mesh,
        in_specs=(P("data", "lane", None, None), P("data"), P("data"), P()),
        out_specs=P(),
    )
    return jax.jit(mapped)


def shard_batch(mesh: Mesh, words, lane_counts, lengths):
    """Device_put a packed batch with the scan step's input shardings.

    Ragged batches (B not divisible by the data axis — the tail of any
    real scan) are padded by repeating the LAST block: padded rows are
    valid hash inputs, and because they duplicate an earlier block they
    can only mark THEMSELVES as duplicates — dup_mask/first_idx for the
    original rows are unchanged.  Callers slice outputs back to their
    input length (`digests[:B]`, `dup[:B]`).
    """
    n_data = mesh.shape["data"]
    b = int(words.shape[0])
    pad = (-b) % n_data
    if pad:
        words = np.concatenate(
            [np.asarray(words)] + [np.asarray(words[-1:])] * pad, axis=0)
        lane_counts = np.concatenate(
            [np.asarray(lane_counts)] + [np.asarray(lane_counts[-1:])] * pad)
        lengths = np.concatenate(
            [np.asarray(lengths)] + [np.asarray(lengths[-1:])] * pad)
    ws = NamedSharding(mesh, P("data", "lane", None, None))
    bs = NamedSharding(mesh, P("data"))
    return (
        jax.device_put(words, ws),
        jax.device_put(lane_counts, bs),
        jax.device_put(lengths, bs),
    )
