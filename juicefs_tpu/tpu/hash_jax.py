"""Batched JTH-256 on TPU: XLA (jnp/lax.scan) and Pallas implementations.

Both compute the exact spec in jth256.py and must produce byte-identical
digests to the numpy reference (BASELINE.md acceptance bar). The work per
row step is a ~6-op uint32 ARX chain over a (B*M*128)-wide vector, so the
kernel is HBM-bandwidth bound: each 64 KiB lane is read once. The XLA path
expresses the 128-row chain as lax.scan (static trip count, fuses into one
loop); the Pallas path keeps a whole lane tile in VMEM and unrolls the row
loop, double-buffered across the grid by the Pallas pipeline.

Shapes are static: callers pad batches to (B, M, 128, 128) via
jth256.pack_blocks, so each (B, M) pair compiles once and is cached.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .jth256 import (
    COLS,
    IV,
    LANE_BYTES,
    ROWS,
    digests_to_bytes,
    pack_blocks,
)
# the reference hash FUNCTION (the package re-exports the name `jth256`,
# shadowing the submodule attribute — import the callable directly)
from .jth256 import jth256 as _jth256_ref

# Plain ints here: wrapping them in jnp.uint32 at module scope would
# initialize a JAX backend at import time, breaking accelerator-free
# environments (the CPU fallback path must import cleanly). Each use below
# casts under trace via _u32().
_P1 = 0x9E3779B1
_P2 = 0x85EBCA77
_P3 = 0xC2B2AE3D
_P4 = 0x27D4EB2F
_P5 = 0x165667B1
_FM1 = 0x85EBCA6B
_FM2 = 0xC2B2AE35


def _u32(c: int):
    return jnp.uint32(c)


def _rotl(x, k: int):
    return (x << jnp.uint32(k)) | (x >> jnp.uint32(32 - k))


def _fmix(x):
    x = x ^ (x >> jnp.uint32(16))
    x = x * _u32(_FM1)
    x = x ^ (x >> jnp.uint32(13))
    x = x * _u32(_FM2)
    return x ^ (x >> jnp.uint32(16))


def _row_chain_scan(words: jax.Array, s0: jax.Array) -> jax.Array:
    """128-row mixing chain via lax.scan. words (B,M,128,128), s0 (B,M,128)."""

    def step(s, w):
        s = (s ^ w) * _u32(_P1)
        s = _rotl(s, 13) * _u32(_P2)
        s = s ^ (s >> jnp.uint32(15))
        return s, None

    s, _ = lax.scan(step, s0, jnp.moveaxis(words, 2, 0))
    return s


def _lane_states(words: jax.Array, lane_offset=0) -> jax.Array:
    """Initial row-chain states. lane_offset shifts the per-lane tweak so a
    lane-sharded device computes with its *global* lane indices."""
    b, m = words.shape[0], words.shape[1]
    j = jnp.arange(COLS, dtype=jnp.uint32)
    lanes = jnp.arange(m, dtype=jnp.uint32) + jnp.uint32(lane_offset)
    s0 = _u32(_P5) ^ (j * _u32(_P1))[None, None, :] ^ (lanes * _u32(_P3))[None, :, None]
    return jnp.broadcast_to(s0, (b, m, COLS))


def _lane_accs(s: jax.Array, lane_offset=0) -> jax.Array:
    """Fold lane states (B,M,128) -> per-lane digests (B,M,8)."""
    b, m = s.shape[0], s.shape[1]
    lanes = jnp.arange(m, dtype=jnp.uint32) + jnp.uint32(lane_offset)
    k8 = jnp.arange(8, dtype=jnp.uint32)
    g = s.reshape(b, m, 16, 8)
    acc = jnp.broadcast_to(
        _u32(_P4) ^ (lanes * _u32(_P2))[None, :, None] ^ (k8 * _u32(_P1))[None, None, :],
        (b, m, 8),
    )
    for gi in range(16):
        acc = _rotl((acc ^ g[:, :, gi, :]) * _u32(_P3), 11) + jnp.uint32(gi) * _u32(_P5)
    return acc


def _combine_accs(
    acc: jax.Array, lane_counts: jax.Array, lengths: jax.Array
) -> jax.Array:
    """Sequentially combine per-lane digests (B,M,8) -> digests (B,8)."""
    b, m = acc.shape[0], acc.shape[1]
    lanes = jnp.arange(m, dtype=jnp.uint32)
    k8 = jnp.arange(8, dtype=jnp.uint32)
    h0 = jnp.broadcast_to(jnp.asarray(IV, dtype=jnp.uint32), (b, 8))
    counts = lane_counts.astype(jnp.uint32)

    def lane_step(h, inp):
        d, li = inp
        hn = _rotl((h ^ d) * _u32(_P2), 17) + li * _u32(_P1)
        live = (counts > li)[:, None]
        return jnp.where(live, hn, h), None

    h, _ = lax.scan(lane_step, h0, (jnp.moveaxis(acc, 1, 0), lanes))
    h = h ^ (lengths.astype(jnp.uint32)[:, None] + k8[None, :] * _u32(_P4))
    return _fmix(h)


def _finish(
    s: jax.Array, lane_counts: jax.Array, lengths: jax.Array
) -> jax.Array:
    """Fold lane states (B,M,128) -> digests (B,8), per the spec."""
    return _combine_accs(_lane_accs(s), lane_counts, lengths)


@functools.partial(jax.jit, static_argnames=())
def hash_packed_jax(
    words: jax.Array, lane_counts: jax.Array, lengths: jax.Array
) -> jax.Array:
    """XLA path: (B, M, 128, 128) uint32 -> (B, 8) uint32 digests."""
    return _finish(_row_chain_scan(words, _lane_states(words)), lane_counts, lengths)


# ---------------------------------------------------------------------------
# Pallas path: one grid step = one lane tile resident in VMEM.
# ---------------------------------------------------------------------------

_LANE_GROUP = 16  # lanes per grid step (16 x 64 KiB in VMEM); measured
# fastest on v5e: 8 -> 108 GiB/s, 16 -> 118/183 GiB/s (16/32 GiB scans),
# 32 -> 110 GiB/s. The output block stays (16,128)-tileable.

# Pallas execution-mode control (VERDICT r2 weak #2: the interpret fallback
# must never be silent). None = auto (compiled iff default backend is TPU);
# True/False forces the mode. The mode actually used by the last
# hash_packed_pallas call is recorded and queryable via last_pallas_mode(),
# so tests and bench.py can *assert* a compiled run instead of trusting it.
_INTERPRET_OVERRIDE: bool | None = None
_LAST_PALLAS_MODE: str | None = None


def set_pallas_interpret(value: bool | None) -> None:
    """Force pallas interpret mode on/off, or None to restore auto."""
    global _INTERPRET_OVERRIDE
    _INTERPRET_OVERRIDE = value


def pallas_interpret_active() -> bool:
    """The interpret flag the next pallas call will use."""
    if _INTERPRET_OVERRIDE is not None:
        return _INTERPRET_OVERRIDE
    return jax.default_backend() != "tpu"


def last_pallas_mode() -> str | None:
    """'compiled' | 'interpret' for the most recent pallas hash, else None."""
    return _LAST_PALLAS_MODE


def _pallas_row_chain(
    words_flat: jax.Array, m: int, tweak: jax.Array, unroll: int = 8,
    interpret: bool = False, lane_group: int | None = None,
) -> jax.Array:
    """words_flat (L, 128, 128) -> lane states (L, 128); L = B*M lanes.

    One grid step keeps `lane_group` lane tiles (x 64 KiB) resident in
    VMEM and runs their row chains together; the Pallas pipeline
    double-buffers the HBM->VMEM streaming across grid steps.

    `tweak` (uint32 (1,)) is xor'ed into every word INSIDE the kernel —
    benchmark loops vary it per iteration to defeat dispatch elision
    without materializing a tweaked copy of the batch in HBM (the copy
    was round 3's pallas handicap: pallas_call is opaque to XLA fusion,
    so `words ^ k` cost one extra HBM write+read per pass).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    group = lane_group or _LANE_GROUP

    def kernel(t_ref, w_ref, out_ref):
        # Constants are rebuilt from Python ints here: a pallas kernel may
        # not close over device arrays created outside the trace.
        p1, p2, p3, p5 = (
            jnp.uint32(0x9E3779B1),
            jnp.uint32(0x85EBCA77),
            jnp.uint32(0xC2B2AE3D),
            jnp.uint32(0x165667B1),
        )
        tw = t_ref[0]
        i = pl.program_id(0)
        u8 = jax.lax.broadcasted_iota(jnp.uint32, (group, 1), 0)
        lane = jax.lax.rem(jnp.uint32(i * group) + u8, jnp.uint32(m))
        j = jax.lax.broadcasted_iota(jnp.uint32, (group, COLS), 1)
        s = p5 ^ (j * p1) ^ (lane * p3)

        def body(r, s):
            for u in range(unroll):
                w = w_ref[:, r * unroll + u, :] ^ tw
                s = (s ^ w) * p1
                s = ((s << jnp.uint32(13)) | (s >> jnp.uint32(19))) * p2
                s = s ^ (s >> jnp.uint32(15))
            return s

        out_ref[:, :] = jax.lax.fori_loop(0, ROWS // unroll, body, s)

    n_lanes = words_flat.shape[0]
    padded = -(-n_lanes // group) * group
    if padded != n_lanes:
        # Pad with zero lanes; their states are computed and discarded.
        words_flat = jnp.concatenate(
            [words_flat, jnp.zeros((padded - n_lanes, ROWS, COLS), jnp.uint32)]
        )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((padded, COLS), jnp.uint32),
        grid=(padded // group,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (group, ROWS, COLS),
                lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec((group, COLS), lambda i: (i, 0)),
        interpret=interpret,
    )(tweak, words_flat)
    return out[:n_lanes]


@functools.partial(jax.jit, static_argnames=("interpret", "lane_group"))
def _hash_packed_pallas_impl(
    words: jax.Array, lane_counts: jax.Array, lengths: jax.Array,
    tweak: jax.Array, interpret: bool, lane_group: int | None = None,
) -> jax.Array:
    b, m = words.shape[0], words.shape[1]
    s = _pallas_row_chain(
        words.reshape(b * m, ROWS, COLS), m, tweak, interpret=interpret,
        lane_group=lane_group,
    ).reshape(b, m, COLS)
    return _finish(s, lane_counts, lengths)


def hash_packed_pallas(
    words: jax.Array,
    lane_counts: jax.Array,
    lengths: jax.Array,
    interpret: bool | None = None,
    tweak: jax.Array | None = None,
    lane_group: int | None = None,
) -> jax.Array:
    """Pallas path: (B, M, 128, 128) uint32 -> (B, 8) uint32 digests.

    interpret=None resolves via pallas_interpret_active(); the resolved mode
    is recorded for last_pallas_mode() so callers can assert a compiled run.
    tweak xors a scalar into every input word inside the kernel (bench
    elision-defeat without an HBM copy); None/0 hashes the words as-is.
    """
    global _LAST_PALLAS_MODE
    mode = pallas_interpret_active() if interpret is None else interpret
    _LAST_PALLAS_MODE = "interpret" if mode else "compiled"
    if tweak is None:
        tweak = jnp.zeros((1,), jnp.uint32)
    else:
        tweak = tweak.reshape((1,)).astype(jnp.uint32)
    return _hash_packed_pallas_impl(
        words, lane_counts, lengths, tweak, interpret=mode,
        lane_group=lane_group,
    )


_IMPLS = {"xla": hash_packed_jax, "pallas": hash_packed_pallas}


def make_hash_fn(impl: str = "xla"):
    """Return the jitted (words, lane_counts, lengths) -> (B,8) hash fn."""
    try:
        return _IMPLS[impl]
    except KeyError:
        raise ValueError(f"unknown hash impl {impl!r} (want xla|pallas)") from None


def hash_blocks_jax(
    blocks, impl: str = "xla", pad_lanes: int | None = None
) -> list[bytes]:
    """Hash a batch of bytes blocks on the default JAX backend."""
    blocks = list(blocks)
    if not blocks:
        return []
    words, counts, lengths = pack_blocks(blocks, pad_lanes=pad_lanes)
    fn = make_hash_fn(impl)
    out = np.asarray(jax.device_get(fn(words, counts, lengths)))
    return digests_to_bytes(out)


def verify_backend(impl: str = "xla", seed: int = 0) -> bool:
    """Self-check: device digests byte-identical to the numpy reference."""
    rng = np.random.default_rng(seed)
    blocks = [
        rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        for n in (0, 1, 100, LANE_BYTES, LANE_BYTES + 7, 3 * LANE_BYTES)
    ]
    dev = hash_blocks_jax(blocks, impl=impl)
    ref = [_jth256_ref(b) for b in blocks]
    return dev == ref
