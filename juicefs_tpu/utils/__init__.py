"""Cross-cutting utilities (reference: pkg/utils).

Provides the logger, a little-endian-free binary Buffer codec used by the
meta key/value schema (reference pkg/utils/buffer.go:25), and clock helpers.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time

_LOG_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
_configured = False
_lock = threading.Lock()


def get_logger(name: str = "juicefs") -> logging.Logger:
    """Process-wide logger (reference pkg/utils/logger.go)."""
    global _configured
    with _lock:
        if not _configured:
            level = os.environ.get("JFS_LOG_LEVEL", "WARNING").upper()
            logging.basicConfig(format=_LOG_FORMAT, level=level)
            _configured = True
    return logging.getLogger(name)


class Buffer:
    """Big-endian binary writer/reader (reference pkg/utils/buffer.go:25).

    The meta engines encode Attr records and KV keys big-endian so that
    byte-wise key order equals numeric order (reference pkg/meta/tkv.go:165).
    """

    __slots__ = ("_b", "_off")

    def __init__(self, data: bytes = b""):
        self._b = bytearray(data)
        self._off = 0

    # -- writing ----------------------------------------------------------
    def put8(self, v: int) -> "Buffer":
        self._b += struct.pack(">B", v & 0xFF)
        return self

    def put16(self, v: int) -> "Buffer":
        self._b += struct.pack(">H", v & 0xFFFF)
        return self

    def put32(self, v: int) -> "Buffer":
        self._b += struct.pack(">I", v & 0xFFFFFFFF)
        return self

    def put64(self, v: int) -> "Buffer":
        self._b += struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF)
        return self

    def put(self, data: bytes) -> "Buffer":
        self._b += data
        return self

    # -- reading ----------------------------------------------------------
    def get8(self) -> int:
        v = self._b[self._off]
        self._off += 1
        return v

    def get16(self) -> int:
        (v,) = struct.unpack_from(">H", self._b, self._off)
        self._off += 2
        return v

    def get32(self) -> int:
        (v,) = struct.unpack_from(">I", self._b, self._off)
        self._off += 4
        return v

    def get64(self) -> int:
        (v,) = struct.unpack_from(">Q", self._b, self._off)
        self._off += 8
        return v

    def get(self, n: int) -> bytes:
        v = bytes(self._b[self._off : self._off + n])
        self._off += n
        return v

    def has_more(self) -> bool:
        return self._off < len(self._b)

    def remaining(self) -> int:
        return len(self._b) - self._off

    def bytes(self) -> bytes:
        return bytes(self._b)


def now() -> float:
    return time.time()


def now_ns() -> int:
    return time.time_ns()


def align_up(n: int, a: int) -> int:
    return (n + a - 1) // a * a


class Cond:
    """Condition with wait-timeout helper (reference pkg/utils/cond.go)."""

    def __init__(self, lock: threading.Lock | None = None):
        self._cond = threading.Condition(lock or threading.Lock())

    def __enter__(self):
        self._cond.__enter__()
        return self

    def __exit__(self, *a):
        return self._cond.__exit__(*a)

    def wait(self, timeout: float | None = None) -> bool:
        return self._cond.wait(timeout)

    def notify_all(self) -> None:
        self._cond.notify_all()
