"""Runtime txn rerun-purity harness (ISSUE 12): the dynamic complement
to ``tools/analyze``'s ``txn-purity`` static pass, the way lockwatch is
the dynamic complement of the lock-order passes.

Opt-in via ``JUICEFS_TXN_RERUN=1`` + :func:`install` (tests/conftest
does both, so the whole tier-1 suite runs instrumented).  Every engine
transaction seam (``tkv_client.MemKV/SqliteKV``, ``redis_kv.RedisKV``,
``sql.SQLMeta._txn/_rtxn``) routes its closure through
:func:`double_run`, which executes every SUCCESSFUL closure TWICE with
the first run's engine-side writes discarded (buffered-write engines
simply drop the buffer; sqlite engines roll back to a savepoint), then
asserts the two runs are byte-identical:

* the ordered write set (buffered KV writes, recorded ``set``/``delete``
  calls, recorded mutating SQL statements) must match exactly;
* the returned result must be structurally equal
  (:func:`canon` — bytes-normalized, address-free);
* a discard/abort decision must reproduce.

Any divergence is a NON-IDEMPOTENT closure: exactly the double-apply
bug that optimistic conflict retry (redis WATCH, sqlite BUSY) triggers
in production, surfaced deterministically on every test run.  Clock
nondeterminism is removed instead of tolerated: while a doubled run is
in flight, ``time.time``/``time.monotonic`` are patched (refcounted, so
ambient code pays nothing when no txn is doubling) and the second run
REPLAYS the first run's readings (thread-local; other threads always
see the real clock) — a closure stamping ``mtime`` is rerun-safe, a
closure appending to a captured list is caught.

Engines that serialize their transactions (MemKV's lock, sqlite's write
mutex, sqlite snapshot reads) compare strictly.  Redis transactions can
race a concurrent writer between the two runs, so their ``run_once``
also returns the READ SET (the WATCH+GET cache plus a scan log): the
purity contract is *writes are a deterministic function of reads*, so a
divergent write set only counts as a violation when the two runs read
identical state — a contended counter bump whose reruns see different
bases is the conflict machinery's business (WATCH aborts the stale
EXEC), not a purity bug.

Violations accumulate in a process-global state; the conftest fixture
fails any test that added one.  Drills use :func:`scoped_state`.
"""

from __future__ import annotations

import os
import re
import threading
import _thread

_REAL_TIME = __import__("time").time
_REAL_MONO = __import__("time").monotonic

_tls = threading.local()

_MUTATING_SQL = ("INSERT", "UPDATE", "DELETE", "REPLACE", "CREATE", "DROP")


def enabled() -> bool:
    return os.environ.get("JUICEFS_TXN_RERUN", "") not in ("", "0")


# ---------------------------------------------------------------------------
# state (mirrors lockwatch.State)

class State:
    def __init__(self):
        self._mu = _thread.allocate_lock()
        self.violations: list[dict] = []
        self.doubled = 0          # closures actually executed twice

    def note(self, engine: str, closure, detail: str) -> None:
        with self._mu:
            self.violations.append({
                "kind": "txn-rerun",
                "engine": engine,
                "closure": _closure_site(closure),
                "detail": detail,
                "thread": threading.current_thread().name,
            })

    def snapshot(self) -> list[dict]:
        with self._mu:
            return list(self.violations)

    def reset(self) -> None:
        with self._mu:
            self.violations.clear()
            self.doubled = 0


_state = State()


def state() -> State:
    return _state


def violations() -> list[dict]:
    return _state.snapshot()


def reset() -> None:
    _state.reset()


class scoped_state:
    """Fresh State for a drill; restores the old one on exit."""

    def __enter__(self) -> State:
        global _state
        self._saved = _state
        _state = State()
        return _state

    def __exit__(self, *exc) -> None:
        global _state
        _state = self._saved


def _closure_site(fn) -> str:
    code = getattr(fn, "__code__", None)
    if code is None:
        return getattr(fn, "__qualname__", repr(fn))
    name = getattr(fn, "__qualname__", code.co_name)
    short = os.path.basename(code.co_filename)
    return f"{name} ({short}:{code.co_firstlineno})"


# ---------------------------------------------------------------------------
# deterministic clock: record on run 1, replay on run 2

class _Clock:
    __slots__ = ("mode", "values", "idx")

    def __init__(self, mode: str, values=None):
        self.mode = mode            # "record" | "replay"
        self.values = values if values is not None else {"t": [], "m": []}
        self.idx = {"t": 0, "m": 0}

    def tick(self, kind: str, real) -> float:
        if self.mode == "record":
            v = real()
            self.values[kind].append(v)
            return v
        vs = self.values[kind]
        i = self.idx[kind]
        if i < len(vs):
            self.idx[kind] = i + 1
            return vs[i]
        # the rerun read the clock MORE times than the first run did —
        # already a divergence the write/result compare will surface;
        # keep time monotone-ish by holding the last reading
        return vs[-1] if vs else real()


def _patched_time():
    c = getattr(_tls, "clock", None)
    return _REAL_TIME() if c is None else c.tick("t", _REAL_TIME)


def _patched_monotonic():
    c = getattr(_tls, "clock", None)
    return _REAL_MONO() if c is None else c.tick("m", _REAL_MONO)


# The clock is patched ONLY while a doubled run is in flight (refcounted
# across threads): a permanently-installed wrapper taxes every
# time.time() on the hot read path (the tracer-overhead budget measured
# it), whereas two module setattrs per doubled txn are noise.  Other
# threads hitting the wrapper mid-scope have no thread-local recorder
# and fall through to the real clock.
_patch_mu = _thread.allocate_lock()
_patch_depth = 0


def _patch_clock() -> None:
    global _patch_depth
    import time as _time

    with _patch_mu:
        _patch_depth += 1
        if _patch_depth == 1:
            _time.time = _patched_time
            _time.monotonic = _patched_monotonic


def _unpatch_clock() -> None:
    global _patch_depth
    import time as _time

    with _patch_mu:
        _patch_depth -= 1
        if _patch_depth == 0:
            _time.time = _REAL_TIME
            _time.monotonic = _REAL_MONO


class _clock_scope:
    def __init__(self, mode: str, values=None):
        self._clock = _Clock(mode, values)

    def __enter__(self) -> _Clock:
        self._saved = getattr(_tls, "clock", None)
        _tls.clock = self._clock
        _patch_clock()
        return self._clock

    def __exit__(self, *exc) -> None:
        _tls.clock = self._saved
        _unpatch_clock()


_installed = False


def install() -> bool:
    """Arm the harness (the clock sources are patched per doubled run,
    not globally — ambient code pays nothing).  Idempotent; no-op
    (returns False) while JUICEFS_TXN_RERUN is unset."""
    global _installed
    if _installed or not enabled():
        return _installed
    _installed = True
    return True


def uninstall() -> None:
    global _installed
    _installed = False


def active() -> bool:
    return _installed and enabled()


# ---------------------------------------------------------------------------
# structural equality (address-free, bytes-normalized)

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def canon(v, depth: int = 0):
    """Canonical comparable form of a closure result / write value."""
    if depth > 8:
        return _ADDR_RE.sub("0x", repr(v))[:200]
    if isinstance(v, (bytes, bytearray, memoryview)):
        return bytes(v)
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(canon(x, depth + 1) for x in v)
    if isinstance(v, (set, frozenset)):
        return frozenset(canon(x, depth + 1) for x in v)
    if isinstance(v, dict):
        return tuple(sorted(
            ((canon(k, depth + 1), canon(x, depth + 1)) for k, x in v.items()),
            key=repr))
    d = getattr(v, "__dict__", None)
    if d is not None:
        return (type(v).__name__,) + tuple(
            sorted((k, canon(x, depth + 1)) for k, x in d.items()))
    return _ADDR_RE.sub("0x", repr(v))[:200]


def _diff(r1, w1, d1, r2, w2, d2) -> str:
    parts = []
    if d1 != d2:
        parts.append(f"discard decision diverged ({d1} vs {d2})")
    if canon(w1) != canon(w2):
        parts.append(
            f"write set diverged (run1={_summ(w1)} run2={_summ(w2)})")
    if canon(r1) != canon(r2):
        parts.append(
            f"result diverged (run1={_summ(r1)} run2={_summ(r2)})")
    return "; ".join(parts)


def _summ(v) -> str:
    return _ADDR_RE.sub("0x", repr(v))[:160]


# ---------------------------------------------------------------------------
# the seam: engines call this with their one-attempt runner

def double_run(engine: str, fn, run_once, reset=None):
    """Run ``run_once() -> (result, writes, discarded[, reads])`` once;
    while the harness is active and the attempt did not discard, discard
    its engine-side effects via ``reset()`` (None for buffered-write
    engines) and run it again under the replayed clock, comparing the
    two runs.  Returns the LAST run's (result, writes, discarded) — for
    direct-write engines that is the run whose effects are live.

    The optional 4th element is the attempt's READ SET, supplied by
    engines whose reads can race concurrent writers (redis): when the
    two runs observed DIFFERENT state, a divergent output is the
    concurrent writer's doing (the engine's conflict machinery owns that
    case) and is not flagged — the contract is writes-as-a-function-of-
    reads, not writes-frozen-in-time."""
    if not active():
        return run_once()[:3]
    with _clock_scope("record") as clk:
        out1 = run_once()
    r1, w1, d1 = out1[:3]
    reads1 = out1[3] if len(out1) > 3 else None
    if d1:
        return r1, w1, d1
    if reset is not None:
        reset()
    try:
        with _clock_scope("replay", clk.values):
            out2 = run_once()
    except BaseException as e:
        # Only serialized engines (no read set) flag a rerun-raise as a
        # violation: on a reads-bearing engine a concurrent writer can
        # legitimately change what the rerun observes (same exemption as
        # the compare path), and an engine-retryable error (sqlite BUSY)
        # is the caller's backoff loop's business, not impurity.
        import sqlite3
        if reads1 is None and not isinstance(e, sqlite3.OperationalError):
            _state.note(engine, fn,
                        f"rerun raised {type(e).__name__}: {e} (first "
                        "run succeeded) — closure consumes state it "
                        "does not reset")
        raise
    r2, w2, d2 = out2[:3]
    reads2 = out2[3] if len(out2) > 3 else None
    with _state._mu:
        _state.doubled += 1
    detail = _diff(r1, w1, d1, r2, w2, d2)
    if detail and (reads1 is None or canon(reads1) == canon(reads2)):
        _state.note(engine, fn, detail)
    return r2, w2, d2


# ---------------------------------------------------------------------------
# SQL cursor recorder (meta/sql.py): the write set of a relational txn
# is the ordered stream of mutating statements it issued

class RecordingCursor:
    """Cursor proxy logging mutating statements; everything else
    delegates.  ``execute`` returns the proxy so chained ``.fetchone()``
    and ``for row in cur.execute(...)`` keep working."""

    def __init__(self, cur):
        self._cur = cur
        self.log: list = []

    @staticmethod
    def _mutating(sql: str) -> bool:
        head = sql.lstrip().split(None, 1)
        return bool(head) and head[0].upper() in _MUTATING_SQL

    def execute(self, sql, params=()):
        if self._mutating(sql):
            self.log.append((sql, canon(tuple(params))))
        self._cur.execute(sql, params)
        return self

    def executemany(self, sql, seq):
        seq = list(seq)  # materialize: recorded AND executed once
        if self._mutating(sql):
            self.log.append((sql, canon(tuple(tuple(p) for p in seq))))
        self._cur.executemany(sql, seq)
        return self

    def __iter__(self):
        return iter(self._cur)

    def __getattr__(self, name):
        return getattr(self._cur, name)
