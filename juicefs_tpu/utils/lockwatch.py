"""Runtime lock-order watchdog (ISSUE 7): the dynamic complement to
``tools/analyze``'s static passes, covering what an AST walk cannot see
through dynamic dispatch.

Opt-in via ``JUICEFS_LOCK_WATCHDOG=1`` + :func:`install` (tests/conftest
does both, so the whole tier-1 suite runs instrumented).  ``install()``
patches, for callers inside ``juicefs_tpu/`` only (the creation site's
frame decides — stdlib and test-code locks stay raw):

* ``threading.Lock`` / ``threading.RLock`` / ``threading.Condition`` —
  construction returns a watched wrapper.  Locks are classed
  lockdep-style by CREATION SITE (``file:line``): every instance born at
  a site shares one node in the acquisition-order graph, so an inversion
  between two *instances* of the same pair of sites is still caught.

and, process-wide (they only record when the calling thread holds a
watched lock):

* ``Future.result()/.exception()`` on a not-done future,
  ``queue.Queue.get/put`` with ``block=True``, ``threading.Event.wait``
  on an unset event, and ``time.sleep`` — the holds-while-blocking set,
  mirroring the static ``blocking-under-lock`` rule.

Detection is graph-based, not interleaving-based: thread 1 taking A then
B and thread 2 taking B then A is reported even when the schedule never
actually deadlocks — the edge set carries the cycle.  ``Condition.wait``
is handled correctly: the wrapper's ``_release_save`` bookkeeping drops
the condition's own lock for the duration of the wait.

Intentional holds-while-blocking sites wrap the region in
``permit("<reason>")`` — the runtime twin of the static
``# analyze: allow(blocking-under-lock) -- reason`` comment.

Violations accumulate in a process-global state; the conftest fixture
fails any test that added one.  Drills use :func:`scoped_state` for an
isolated graph and :func:`watched_lock` for explicit wrappers.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
import _thread

_JUICEFS_MARK = os.sep + "juicefs_tpu" + os.sep

# real factories captured at import time — the wrappers must build their
# inner primitives from these, never from the (possibly patched)
# threading module attributes
_REAL_LOCK = _thread.allocate_lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_tls = threading.local()


def enabled() -> bool:
    return os.environ.get("JUICEFS_LOCK_WATCHDOG", "") not in ("", "0")


# ---------------------------------------------------------------------------
# state: site-classed acquisition graph + violations

class State:
    """One watchdog universe: edge graph, violation list."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        # (site_a, site_b) -> (thread_name, short_stack): B acquired
        # while A held
        self.edges: dict[tuple[str, str], tuple[str, str]] = {}
        self._adj: dict[str, set[str]] = {}
        self.violations: list[dict] = []

    def note_edge(self, a: "WatchedLock", b: "WatchedLock") -> None:
        key = (a.site, b.site)
        with self._mu:
            if key in self.edges:
                return
            stack = _short_stack()
            self.edges[key] = (threading.current_thread().name, stack)
            if a.site == b.site:
                if b.reentrant:
                    return   # distinct RLock instances of one class: benign
                self.violations.append({
                    "kind": "inversion",
                    "detail": f"nested acquisition of lock class {a.site} "
                              "(two instances, non-reentrant): two threads "
                              "doing this in opposite instance order "
                              "deadlock",
                    "thread": threading.current_thread().name,
                    "stack": stack,
                })
                return
            self._adj.setdefault(a.site, set()).add(b.site)
            path = self._path(b.site, a.site)
            if path is not None:
                prev_thread, prev_stack = self.edges.get(
                    (path[0], path[1]), ("?", ""))
                self.violations.append({
                    "kind": "inversion",
                    "detail": (
                        f"lock-order inversion: {a.site} -> {b.site} here, "
                        f"but {' -> '.join(path)} was established by thread "
                        f"{prev_thread}"),
                    "thread": threading.current_thread().name,
                    "stack": stack + "\n  -- conflicting order:\n"
                             + prev_stack,
                })

    def _path(self, src: str, dst: str):
        """A path src -> ... -> dst in the site graph, else None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_blocking(self, op: str, held: list["WatchedLock"]) -> None:
        with self._mu:
            self.violations.append({
                "kind": "holds-while-blocking",
                "detail": f"{op} while holding "
                          + ", ".join(sorted({h.site for h in held})),
                "thread": threading.current_thread().name,
                "stack": _short_stack(),
            })

    def snapshot(self) -> list[dict]:
        with self._mu:
            return list(self.violations)

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self._adj.clear()
            self.violations.clear()


_state = State()


def state() -> State:
    return _state


def violations() -> list[dict]:
    return _state.snapshot()


def reset() -> None:
    _state.reset()


class scoped_state:
    """Swap in a fresh State for a drill; restores the old one on exit.
    (Tier-1 runs tests serially; background threads recording into the
    drill state merely add noise a drill's presence-assertions ignore.)"""

    def __enter__(self) -> State:
        global _state
        self._saved = _state
        _state = State()
        return _state

    def __exit__(self, *exc) -> None:
        global _state
        _state = self._saved


def _short_stack(limit: int = 14) -> str:
    frames = traceback.extract_stack()[:-3]
    keep = [f for f in frames
            if _JUICEFS_MARK in f.filename or "tests" + os.sep in f.filename]
    tail = (keep or frames)[-4:]
    return "\n".join(f"  {os.path.basename(f.filename)}:{f.lineno} "
                     f"in {f.name}" for f in tail[:limit])


# ---------------------------------------------------------------------------
# thread-held bookkeeping

def _held() -> list:
    try:
        return _tls.stack
    except AttributeError:
        _tls.stack = []
        return _tls.stack


def _permits() -> int:
    return getattr(_tls, "permits", 0)


class permit:
    """Mark a region as an intentionally-blocking-under-lock site.  The
    runtime twin of `# analyze: allow(blocking-under-lock) -- reason`;
    the reason is mandatory and kept for the report."""

    def __init__(self, reason: str):
        if not reason or not reason.strip():
            raise ValueError("lockwatch.permit requires a written reason")
        self.reason = reason

    def __enter__(self):
        _tls.permits = _permits() + 1
        return self

    def __exit__(self, *exc):
        _tls.permits = _permits() - 1


def _note_acquire(lock: "WatchedLock") -> None:
    stack = _held()
    if not any(e is lock for e in stack):   # reentry records no edges
        for h in stack:
            _state.note_edge(h, lock)
    stack.append(lock)


def _note_release(lock: "WatchedLock") -> None:
    stack = _held()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] is lock:
            del stack[i]
            return


# ---------------------------------------------------------------------------
# wrappers

class WatchedLock:
    """threading.Lock-compatible wrapper recording acquisition order."""

    __slots__ = ("_inner", "site")
    reentrant = False

    def __init__(self, site: str, inner=None):
        self._inner = inner if inner is not None else _REAL_LOCK()
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self)
        return ok

    def release(self) -> None:
        _note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<WatchedLock {self.site} inner={self._inner!r}>"


class WatchedRLock:
    """threading.RLock-compatible wrapper, incl. the Condition protocol
    (`_release_save`/`_acquire_restore`/`_is_owned`) with correct
    held-set bookkeeping across a Condition.wait."""

    __slots__ = ("_inner", "site")
    reentrant = True

    def __init__(self, site: str, inner=None):
        self._inner = inner if inner is not None else _REAL_RLOCK()
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self)
        return ok

    def release(self) -> None:
        _note_release(self)
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol: wait() releases ALL recursion levels
    def _release_save(self):
        stack = _held()
        n = sum(1 for e in stack if e is self)
        state = self._inner._release_save()
        for _ in range(n):
            _note_release(self)
        return (state, n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        self._inner._acquire_restore(state)
        for _ in range(n):
            _note_acquire(self)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def __repr__(self) -> str:
        return f"<WatchedRLock {self.site} inner={self._inner!r}>"


def _caller_site(depth: int = 2):
    """(site, is_juicefs) for the construction site `depth` frames up."""
    f = sys._getframe(depth)
    fn = f.f_code.co_filename
    if _JUICEFS_MARK not in fn:
        return None
    mark = fn.rindex(_JUICEFS_MARK)
    short = fn[mark + 1:].replace(os.sep, "/")
    return f"{short}:{f.f_lineno}"


def watched_lock(site: str = "", rlock: bool = False):
    """Explicit wrapper factory (drills, opt-in call sites)."""
    if not site:
        site = _caller_site() or "adhoc"
    return WatchedRLock(site) if rlock else WatchedLock(site)


# ---------------------------------------------------------------------------
# install / uninstall

_installed = False
_saved: dict = {}


def install() -> bool:
    """Patch the factories and the blocking set.  Idempotent; no-op
    (returns False) when JUICEFS_LOCK_WATCHDOG is not set."""
    global _installed
    if _installed or not enabled():
        return _installed
    import queue as _queue
    import time as _time
    from concurrent.futures import Future as _Future

    real_lock = threading.Lock
    real_rlock = threading.RLock
    real_cond = threading.Condition

    def lock_factory():
        site = _caller_site()
        if site is None:
            return real_lock()
        return WatchedLock(site)

    def rlock_factory():
        site = _caller_site()
        if site is None:
            return real_rlock()
        return WatchedRLock(site)

    def condition_factory(lock=None):
        if lock is None:
            site = _caller_site()
            if site is not None:
                lock = WatchedRLock(site)
        return real_cond(lock)

    _saved.update(
        lock=real_lock, rlock=real_rlock, cond=real_cond,
        fut_result=_Future.result, fut_exception=_Future.exception,
        q_get=_queue.Queue.get, q_put=_queue.Queue.put,
        ev_wait=threading.Event.wait, sleep=_time.sleep,
    )
    threading.Lock = lock_factory
    threading.RLock = rlock_factory
    threading.Condition = condition_factory

    _TESTS_MARK = os.sep + "tests" + os.sep

    def _maybe_flag(op):
        stack = _held()
        if not stack or _permits():
            return
        # only juicefs/test CALL SITES count: stdlib-internal waits made
        # on our behalf (e.g. Thread.start's bounded startup handshake
        # inside a lane lock) are not the unbounded blocking this hunts
        caller = sys._getframe(2).f_code.co_filename
        if _JUICEFS_MARK not in caller and _TESTS_MARK not in caller:
            return
        _state.note_blocking(op, stack)

    def result(self, timeout=None, _orig=_Future.result):
        if not self.done():
            _maybe_flag("Future.result()")
        return _orig(self, timeout)

    def exception(self, timeout=None, _orig=_Future.exception):
        if not self.done():
            _maybe_flag("Future.exception()")
        return _orig(self, timeout)

    def q_get(self, block=True, timeout=None, _orig=_queue.Queue.get):
        if block and self.empty():
            _maybe_flag("Queue.get()")
        return _orig(self, block, timeout)

    def q_put(self, item, block=True, timeout=None, _orig=_queue.Queue.put):
        if block and self.full():
            _maybe_flag("Queue.put()")
        return _orig(self, item, block, timeout)

    def ev_wait(self, timeout=None, _orig=threading.Event.wait):
        if not self.is_set():
            _maybe_flag("Event.wait()")
        return _orig(self, timeout)

    def sleep(secs, _orig=_time.sleep):
        if secs > 0:
            _maybe_flag("time.sleep()")
        return _orig(secs)

    _Future.result = result
    _Future.exception = exception
    _queue.Queue.get = q_get
    _queue.Queue.put = q_put
    threading.Event.wait = ev_wait
    _time.sleep = sleep
    _installed = True
    return True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    import queue as _queue
    import time as _time
    from concurrent.futures import Future as _Future

    threading.Lock = _saved["lock"]
    threading.RLock = _saved["rlock"]
    threading.Condition = _saved["cond"]
    _Future.result = _saved["fut_result"]
    _Future.exception = _saved["fut_exception"]
    _queue.Queue.get = _saved["q_get"]
    _queue.Queue.put = _saved["q_put"]
    threading.Event.wait = _saved["ev_wait"]
    _time.sleep = _saved["sleep"]
    _installed = False
