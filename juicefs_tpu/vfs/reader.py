"""DataReader: chunk-aware reads with adaptive, feedback-driven readahead.

Behavioral port of the reference's pkg/vfs/reader.go. The reference runs an
async per-slice state machine (sliceReader NEW/BUSY/READY... reader.go:34-50)
with an adaptive readahead window (checkReadahead :417-439); here reads are
synchronous against the chunk store (whose disk/mem cache and singleflight
already absorb concurrency) while readahead is delegated to the store's
prefetch stage at PREFETCH class.

Epoch-streaming read path (ISSUE 11) — the dataloader shape the volume
exists to serve (many clients scanning shuffled shards every epoch):

  - sequential detection tolerates small out-of-order deliveries around
    `_last_end` (the FUSE kernel splits large reads and the fragments can
    arrive reordered): only a seek OUTSIDE the slack band collapses the
    window, mirroring the reference's two-session heuristic
    (reader.go:276,370-415);
  - the per-handle window doubles while sequential, but growth is gated by
    the live prefetch used/issued ratio (chunk/prefetch.py instance
    counters): a window whose speculation is not being consumed stops
    doubling and shrinks instead of wasting object GETs;
  - a handle that sustains sequential progress past `streaming_after`
    bytes enters STREAMING mode: the window cap escalates from
    `max_readahead` (block granularity) to the file-granularity
    `max_streaming`, bounded by the prefetcher's queue depth — sizing past
    what the PREFETCH class will accept only sheds;
  - readahead PLANNING (the chunk-meta walk) runs on a PREFETCH-class
    task, never the foreground read thread; the plan batches its
    `read_chunks` meta reads into one engine round trip, and a full
    PREFETCH queue sheds the plan (the reservation rolls back) instead of
    stalling the read;
  - at sequential EOF of a streaming handle, an epoch hook warms the NEXT
    shard (the name-ordered sibling file): the store's ring-aware prefetch
    fills the local cache with blocks this member owns and hints cache
    group peers to warm theirs, so epoch N+1 opens hot with zero
    redundant object GETs.
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional

from ..chunk import CachedStore
from ..meta.base import BaseMeta
from ..meta.context import Context
from ..meta.slice import build_slice
from ..meta.types import CHUNK_SIZE, TYPE_FILE
from ..metric import global_registry
from ..metric.trace import global_tracer

DEFAULT_MAX_READAHEAD = 8 << 20
DEFAULT_MAX_STREAMING = 64 << 20
# sustained sequential bytes before a handle escalates to streaming mode
DEFAULT_STREAMING_AFTER = 16 << 20
# reorder slack: offsets within this band of the frontier still count as
# sequential (FUSE splits >=1 MiB reads into fragments it may deliver out
# of order; a fragment landing early must not zero a 64 MiB window)
DEFAULT_SEQ_SLACK = 1 << 20
# used/issued feedback thresholds: below LOW the window shrinks, above
# HIGH it may grow, in between it holds (hysteresis so a noisy ratio
# doesn't oscillate the window every read)
_EFF_LOW = 0.5
_EFF_HIGH = 0.8
_EFF_MIN_ISSUED = 8  # issued delta before the ratio is trusted
# epoch hook: cap on next-shard directory scans (a million-entry dir is
# not a shard layout; scanning it on every EOF would be pure waste)
_EPOCH_DIR_CAP = 65536

_TR = global_tracer()

_reg = global_registry()
_PLANS = _reg.counter(
    "juicefs_readahead_plans",
    "Readahead planning tasks run off the read thread (PREFETCH class)",
)
_PLAN_SHED = _reg.counter(
    "juicefs_readahead_plan_shed",
    "Readahead plans dropped on a saturated PREFETCH queue "
    "(the reservation rolls back; the read never stalls)",
)
_STREAMING = _reg.counter(
    "juicefs_readahead_streaming",
    "Streaming-mode transitions per handle", ("event",),
)
_EPOCH_WARMS = _reg.counter(
    "juicefs_readahead_epoch_warms",
    "Sequential-EOF epoch hooks that warmed the next shard",
)

# aggregate window state over every live reader (multiple mounts sum);
# weak refs so the gauges never pin a closed reader
_LIVE_READERS: "weakref.WeakSet[DataReader]" = weakref.WeakSet()


def _sum_readers(fn) -> float:
    total = 0
    try:
        for dr in list(_LIVE_READERS):
            total += fn(dr)
    except Exception:
        pass  # racing a reader teardown must never break a scrape
    return total


_reg.gauge(
    "juicefs_readahead_window_bytes",
    "Sum of live per-handle readahead windows",
).set_function(lambda: _sum_readers(lambda dr: dr._window_bytes()))
_reg.gauge(
    "juicefs_readahead_streaming_handles",
    "Open handles currently in streaming readahead mode",
).set_function(lambda: _sum_readers(lambda dr: dr._streaming_handles()))


class FileReader:
    """Read state of one open handle (reference fileReader reader.go:69)."""

    def __init__(self, dr: "DataReader", ino: int):
        self.dr = dr
        self.ino = ino
        self._lock = threading.Lock()
        self._last_end = -1
        self._ra_window = 0
        self._ra_done = 0  # readahead already enqueued up to this offset
        self._seq_bytes = 0  # sequential progress toward streaming mode
        self._streaming = False
        self._eof_warmed = False  # epoch hook fired for this pass
        # prefetch-counter snapshot for the window feedback: anchored to
        # the store's CURRENT totals, so a fresh handle's first
        # evaluation measures its own window, not the mount's lifetime
        # ratio (which would pin new handles' ramps to unrelated history)
        _issued, warmed, used, _dropped = dr.store.prefetcher.counters()
        self._eff_warmed = warmed
        self._eff_used = used

    # -- window state machine ----------------------------------------------
    def _is_sequential(self, off: int) -> bool:
        """Sequential continuation, with reorder tolerance: anything
        within `seq_slack` of the frontier (before OR after it) is the
        kernel splitting/reordering a large read, not a random seek."""
        if self._last_end < 0:
            return False
        return abs(off - self._last_end) <= self.dr.seq_slack

    def _efficiency(self) -> Optional[float]:
        """used/WARMED over the window since the last adjustment, or
        None while the signal is too thin to act on.  The counters are
        the owning store's (all handles share them): waste is a
        store-wide budget, and a per-handle split would starve every
        handle of signal at dataloader fan-outs.

        Warmed — completed speculative loads — is the denominator rather
        than raw issued: in a cache group most issued keys are ring-
        forwarded as peer warm HINTS (never warmed locally, so never
        creditable as used), and an issued-based ratio would read
        low-by-construction in exactly the multi-member deployment the
        streaming mode targets.

        The reader's TOTAL lookahead gap (planned-but-not-yet-read
        blocks across every open handle — the handles share this store's
        counters) is CREDITED to the numerator: a freshly warmed block
        ahead of a frontier is not waste, it is the whole point — without
        the credit a multi-handle ramp reads as a low ratio and the
        feedback would fight the doubling it gates.  To keep the credit
        from masking real waste, an evaluation only triggers once the
        warmed delta spans at least twice the gap: warmed-then-evicted
        blocks then dominate the window and the ratio reads low."""
        fetcher = self.dr.store.prefetcher
        _issued, warmed, used, _dropped = fetcher.counters()
        d_warmed = warmed - self._eff_warmed
        gap = self.dr.lookahead_gap_blocks()
        if d_warmed < max(_EFF_MIN_ISSUED, 2 * gap):
            return None
        d_used = used - self._eff_used
        self._eff_warmed, self._eff_used = warmed, used
        return max(0.0, (d_used + gap) / d_warmed)

    def _advance_window(self, size: int) -> None:
        """Called under self._lock on each sequential read."""
        bs = self.dr.store.conf.block_size
        self._seq_bytes += size
        if (not self._streaming and self.dr.streaming
                and self._seq_bytes >= self.dr.streaming_after):
            self._streaming = True
            _STREAMING.labels("enter").inc()
        cap = self.dr.streaming_cap() if self._streaming \
            else self.dr.max_readahead
        eff = self._efficiency()
        if eff is not None and eff < _EFF_LOW and self._ra_window > bs:
            # issued blocks are not being consumed: shrink instead of
            # paying object GETs for speculation nothing reads
            self._ra_window = max(bs, self._ra_window // 2)
        elif eff is None or eff >= _EFF_HIGH:
            self._ra_window = min(cap, max(self._ra_window * 2, bs))
        else:
            self._ra_window = min(cap, max(self._ra_window, bs))

    def _collapse(self) -> None:
        """A true random seek (outside the slack band): drop all
        speculative state, exit streaming."""
        self._ra_window = 0
        self._ra_done = 0
        self._seq_bytes = 0
        self._eof_warmed = False  # re-arm: a wrapped handle is a new epoch
        # re-anchor the feedback snapshots: the seek abandoned this
        # handle's planned-but-unread speculation, which would otherwise
        # count in the next evaluation's warmed-delta but never in used —
        # a spurious shrink on the new pass's first window
        _issued, warmed, used, _dropped = \
            self.dr.store.prefetcher.counters()
        self._eff_warmed = warmed
        self._eff_used = used
        if self._streaming:
            self._streaming = False
            _STREAMING.labels("exit").inc()

    def read(self, ctx: Context, off: int, size: int) -> tuple[int, bytes]:
        """Returns (errno, buffer). The buffer may be a zero-copy
        memoryview into a cached block on the single-segment fast path —
        callers (fuse writev reply, fs.pread accumulation) treat it as a
        read-only bytes-like."""
        st, attr = self.dr.meta.getattr(ctx, self.ino)
        if st != 0:
            return st, b""
        length = attr.length
        # Read-your-writes: an open writer may hold a longer buffered length.
        wlen = self.dr.writer_length(self.ino)
        if wlen is not None:
            length = max(length, wlen)
        if off >= length or size <= 0:
            return 0, b""
        size = min(size, length - off)

        end = off + size
        indx, coff = divmod(off, CHUNK_SIZE)
        if coff + size <= CHUNK_SIZE:
            # fast path: the read lives in one chunk — hand its buffer
            # through without reassembly (the dominant shape: FUSE reads
            # are <=1 MiB, chunks are 64 MiB)
            st, out = self._read_chunk(indx, coff, size)
            if st != 0:
                return st, b""
        else:
            parts = []
            pos = off
            while pos < end:
                indx, coff = divmod(pos, CHUNK_SIZE)
                n = min(end - pos, CHUNK_SIZE - coff)
                st, data = self._read_chunk(indx, coff, n)
                if st != 0:
                    return st, b""
                parts.append(data)
                pos += n
            out = b"".join(parts)

        epoch = False
        with self._lock:
            if self._is_sequential(off):
                if end > self._last_end:
                    # growth requires forward PROGRESS: a stationary
                    # re-read of one hot offset sits inside the slack
                    # band forever, and advancing on it would ramp a
                    # streaming window ahead of a frontier that never
                    # moves (pure prefetch waste).  Credit only the NET
                    # advance — overlapping strided reads must not
                    # double-count their overlap toward streaming_after
                    self._advance_window(end - self._last_end)
                    self._last_end = end
                # else: reorder tolerance — a fragment landing BEHIND
                # the frontier keeps the state but earns no growth (its
                # leading sibling already advanced for the whole read)
            else:
                self._collapse()
                # a true seek MOVES the frontier (a rewound handle — the
                # next epoch over the same fd — re-establishes the
                # sequential pattern from its new position; keeping the
                # old high-water mark would classify every read of the
                # new pass as random forever)
                self._last_end = end
            window = self._ra_window
            # only plan the part of the window not already enqueued —
            # re-walking warmed blocks costs a meta read + queue churn
            # per request (reference reader.go keeps per-session state)
            ra_start = max(end, self._ra_done)
            ra_end = min(end + window, length)
            self._ra_done = max(self._ra_done, ra_end)
            if (self._streaming and end >= length
                    and not self._eof_warmed):
                # sequential EOF on a streaming handle: one epoch hook
                self._eof_warmed = True
                epoch = True
        if window > 0 and ra_end > ra_start:
            # plan OFF the read thread (PREFETCH class): the chunk-meta
            # walk never costs the foreground read a round trip, and a
            # saturated queue sheds the plan instead of stalling here
            if not self.dr.submit_plan(self, ra_start, ra_end - ra_start):
                with self._lock:
                    # roll the reservation back (only the part nothing
                    # else advanced past) so a later read re-plans it
                    if self._ra_done == ra_end:
                        self._ra_done = ra_start
        if epoch:
            self.dr.submit_epoch_warm(ctx, self.ino)
        return 0, out

    def _read_chunk(self, indx: int, coff: int, size: int) -> tuple[int, bytes]:
        st, slices = self.dr.meta.read_chunk(self.ino, indx)
        if st != 0:
            return st, b""
        view = build_slice(slices)
        end = coff + size
        segs = []  # (s0, s1, seg) overlapping non-hole segments
        for seg in view:
            s0 = max(seg.pos, coff)
            s1 = min(seg.pos + seg.len, end)
            if s0 < s1 and seg.id != 0:
                segs.append((s0, s1, seg))
        if len(segs) == 1 and segs[0][0] == coff and segs[0][1] == end:
            # one slice covers the whole request, no holes: hand the
            # store's buffer (often a zero-copy view of a cached block)
            # straight through without the assembly bytearray
            s0, s1, seg = segs[0]
            return 0, self._read_seg(seg, s0, s1)
        out = bytearray(size)
        if len(segs) > 1:
            # fragmented chunk (the pre-compaction case: many small slices
            # from overwrites): fan the per-slice loads out instead of
            # walking them serially (VERDICT r3 weak #6; reference
            # reader.go:160 runs every sliceReader as its own goroutine).
            # A dedicated pool avoids nested-submit deadlock with the
            # store's block-level download pool, which RSlice.read may
            # itself use for multi-block spans.
            ref = _TR.current_ref()  # span ref crosses the pool explicitly
            futs = [
                (s0, self.dr.spool.submit(self._read_seg, seg, s0, s1, ref))
                for s0, s1, seg in segs
            ]
            for s0, fut in futs:
                data = fut.result()
                out[s0 - coff : s0 - coff + len(data)] = data
        elif segs:
            s0, s1, seg = segs[0]
            data = self._read_seg(seg, s0, s1)
            out[s0 - coff : s0 - coff + len(data)] = data
        return 0, bytes(out)  # multi-seg/hole case: out was assembled here

    def _read_seg(self, seg, s0: int, s1: int, parent=None) -> bytes:
        rs = self.dr.store.new_reader(seg.id, seg.size)
        return rs.read(seg.off + (s0 - seg.pos), s1 - s0, parent=parent)

    def _readahead(self, off: int, size: int) -> None:
        """Warm the blocks backing [off, off+size) via the prefetch
        stage.  Runs at PREFETCH class (DataReader.submit_plan), never on
        the read thread; the chunk-meta walk batches into one
        `read_chunks` engine round trip."""
        end = off + size
        first = off // CHUNK_SIZE
        last = (end - 1) // CHUNK_SIZE
        indxs = list(range(first, last + 1))
        for indx, (st, slices) in zip(
                indxs, self.dr.meta.read_chunks(self.ino, indxs)):
            if st != 0:
                return
            coff = max(off - indx * CHUNK_SIZE, 0)
            cend = min(end - indx * CHUNK_SIZE, CHUNK_SIZE)
            for seg in build_slice(slices):
                s0 = max(seg.pos, coff)
                s1 = min(seg.pos + seg.len, cend)
                if s0 < s1 and seg.id != 0:
                    self.dr.store.prefetch(
                        seg.id, seg.size, seg.off + (s0 - seg.pos), s1 - s0
                    )

    # -- observability ------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "window": self._ra_window,
                "streaming": self._streaming,
                "seq_bytes": self._seq_bytes,
                "frontier": self._last_end,
            }


class DataReader:
    """Per-mount reader factory (reference DataReader reader.go:69-79)."""

    def __init__(
        self,
        meta: BaseMeta,
        store: CachedStore,
        max_readahead: int = DEFAULT_MAX_READAHEAD,
        writer=None,
        streaming: bool = True,
        streaming_after: int = DEFAULT_STREAMING_AFTER,
        max_streaming: int = DEFAULT_MAX_STREAMING,
        seq_slack: int = DEFAULT_SEQ_SLACK,
    ):
        self.meta = meta
        self.store = store
        self.max_readahead = max_readahead
        self.streaming = streaming
        self.streaming_after = max(0, streaming_after)
        self.max_streaming = max(max_streaming, max_readahead)
        self.seq_slack = max(0, seq_slack)
        self._writer = writer
        self._handles: "weakref.WeakSet[FileReader]" = weakref.WeakSet()
        # slice-level fan-out for fragmented chunks on the unified
        # scheduler's "slice" lane — a separate lane from the store's
        # block-level "download" lane so nested submits cannot deadlock
        # (ISSUE 6; docs/ARCHITECTURE.md "Concurrency model")
        from ..qos import IOClass

        self.spool = store.scheduler.executor(
            "slice", IOClass.FOREGROUND, width=store.conf.max_download)
        # readahead planning + epoch warming (ISSUE 11): PREFETCH class on
        # the slice lane — plans submit block fetches to the download lane
        # (slice -> download, the declared direction) and never wait on
        # them, and a full queue sheds the plan rather than backpressuring
        # the read thread
        self.ppool = store.scheduler.executor("slice", IOClass.PREFETCH)
        # dataset-manifest epoch hint (ISSUE 13 satellite): exact
        # ino -> next-shard-ino successor map installed via the `.control`
        # epoch_plan op; empty = fall back to the name-order readdir guess
        self._epoch_plan: dict[int, int] = {}
        _LIVE_READERS.add(self)

    def set_epoch_plan(self, plan: dict[int, int]) -> None:
        """Install (or clear) the manifest-driven next-shard plan: the
        sequential-EOF epoch hook warms plan[ino] instead of guessing
        the name-ordered sibling."""
        self._epoch_plan = dict(plan)

    def open(self, ino: int) -> FileReader:
        fr = FileReader(self, ino)
        self._handles.add(fr)
        return fr

    def writer_length(self, ino: int) -> Optional[int]:
        if self._writer is None:
            return None
        return self._writer.get_length(ino)

    def lookahead_gap_blocks(self) -> int:
        """Planned-but-not-yet-consumed blocks across every open handle
        (unlocked reads of two ints per handle: a heuristic input, benign
        races only under- or over-credit one block)."""
        bs = self.store.conf.block_size
        return sum(max(0, fr._ra_done - fr._last_end) // bs
                   for fr in list(self._handles))

    def streaming_cap(self) -> int:
        """Window cap in streaming mode: file-granularity, but bounded by
        what the PREFETCH stage will actually accept — the prefetcher's
        outstanding-fetch depth in blocks (sizing past it only sheds).
        Floored at max_readahead: escalating to streaming must never
        grant LESS window than a short-scan handle gets (small blocks ×
        depth can undercut it)."""
        return max(self.max_readahead,
                   min(self.max_streaming,
                       self.store.prefetcher.depth
                       * self.store.conf.block_size))

    # -- speculative-work dispatch (PREFETCH class, ISSUE 11) --------------
    def submit_plan(self, fr: FileReader, off: int, size: int) -> bool:
        """Queue a readahead plan; False when it was shed (full PREFETCH
        queue or closing reader) — the caller rolls back its reservation."""
        try:
            fut = self.ppool.submit(fr._readahead, off, size)
        except Exception:
            # racing close() (RuntimeError), scheduler backpressure
            # leaking out of a demoted submit (TimeoutError), or anything
            # else: a readahead plan is advisory — shed it, never let the
            # failure reach the read that only wanted to be faster
            fut = None
        if fut is None:
            _PLAN_SHED.inc()
            return False
        _PLANS.inc()
        return True

    def submit_epoch_warm(self, ctx: Context, ino: int) -> None:
        """Queue the sequential-EOF epoch hook (fire-and-forget)."""
        try:
            self.ppool.submit(self._warm_next_shard, ctx, ino)
        except Exception:
            pass  # advisory epoch warm: any dispatch failure is a shed

    def _warm_next_shard(self, ctx: Context, ino: int) -> None:
        """Epoch hook: a streaming handle just finished a shard-shaped
        file; warm the NEXT shard (name-ordered sibling) so epoch N+1
        opens hot.  Every block routes through the store's ring-aware
        prefetch: blocks this member owns fill the local cache, blocks a
        cache-group peer owns become warm hints to that peer — between
        the members, the whole next shard lands ring-locally."""
        try:
            # manifest-exact plan first (ISSUE 13 satellite): the loader
            # told us the successor, so the readdir guess — and its whole
            # directory scan — is skipped
            nxt_ino = self._epoch_plan.get(ino, 0)
            if not nxt_ino:
                st, attr = self.meta.getattr(ctx, ino)
                if st != 0 or not attr.parent:
                    return  # multi-linked or gone: no unambiguous sibling
                # attr-LESS readdir: the expensive part of a giant listing
                # is the per-entry attr assembly + lease priming
                # (readdirplus), which this deliberately skips — one plain
                # name scan, then a single getattr on the chosen sibling.
                # The cap bounds the sort/scan work on absurd layouts (a
                # 65k+-entry dir is not a shard directory; warming "the
                # next" of it is a guess not worth the walk).
                st, entries = self.meta.readdir(ctx, attr.parent)
                if st != 0 or len(entries) > _EPOCH_DIR_CAP:
                    return
                names = sorted(
                    (e.name, e.inode) for e in entries
                    if not e.name.startswith(b".")
                )
                for i, (_name, entry_ino) in enumerate(names):
                    if entry_ino == ino and i + 1 < len(names):
                        nxt_ino = names[i + 1][1]
                        break
            if not nxt_ino:
                return
            st, nattr = self.meta.getattr(ctx, nxt_ino)
            if st != 0 or nattr.typ != TYPE_FILE or nattr.length <= 0:
                # the name-ordered neighbor is not a readable shard (a
                # subdir, a socket, an empty file): this is a layout
                # guess, not a contract — bail rather than walk further
                return
            length = nattr.length
            # plan at most one prefetcher-depth of blocks: enqueueing past
            # the queue bound only sheds, and the tail warms on demand.
            # The budget clips at BLOCK granularity — a chunk is 64 MiB,
            # so chunk-level clipping alone could enqueue 8x the budget
            # on small-block volumes
            budget = self.store.prefetcher.depth * self.store.conf.block_size
            limit = min(length, budget)
            nchunks = (limit + CHUNK_SIZE - 1) // CHUNK_SIZE
            indxs = list(range(nchunks))
            for indx, (st, slices) in zip(
                    indxs, self.meta.read_chunks(nxt_ino, indxs)):
                if st != 0:
                    return
                cend = min(limit - indx * CHUNK_SIZE, CHUNK_SIZE)
                for seg in build_slice(slices):
                    s0, s1 = seg.pos, min(seg.pos + seg.len, cend)
                    if s0 < s1 and seg.id != 0:
                        self.store.prefetch(seg.id, seg.size,
                                            seg.off, s1 - s0)
            _EPOCH_WARMS.inc()
        except Exception:
            pass  # speculative: an epoch hook must never surface errors

    # -- observability ------------------------------------------------------
    def _window_bytes(self) -> int:
        return sum(fr._ra_window for fr in list(self._handles))

    def _streaming_handles(self) -> int:
        return sum(1 for fr in list(self._handles) if fr._streaming)

    def stats(self) -> dict:
        """Readahead section of `.status` (vfs/internal.py)."""
        handles = list(self._handles)
        issued, warmed, used, dropped = self.store.prefetcher.counters()
        return {
            "streaming_enabled": self.streaming,
            "handles": len(handles),
            "streaming_handles": self._streaming_handles(),
            "window_bytes": self._window_bytes(),
            "max_readahead": self.max_readahead,
            "max_streaming": self.max_streaming,
            "prefetch": {
                "issued": issued, "warmed": warmed, "used": used,
                "dropped": dropped,
                "used_ratio": round(used / issued, 3) if issued else None,
                # the window feedback's actual control signal: in a
                # cache group most issued keys are ring-forwarded hints
                # (never warmed locally), so used/issued reads low there
                # by construction — steer by used/warmed
                "feedback_ratio": round(used / warmed, 3)
                if warmed else None,
            },
        }

    def close(self) -> None:
        self.ppool.shutdown(wait=False, cancel_futures=True)
        self.spool.shutdown(wait=False)
