"""DataReader: chunk-aware reads with adaptive readahead.

Behavioral port of the reference's pkg/vfs/reader.go. The reference runs an
async per-slice state machine (sliceReader NEW/BUSY/READY... reader.go:34-50)
with an adaptive readahead window (checkReadahead :417-439); here reads are
synchronous against the chunk store (whose disk/mem cache and singleflight
already absorb concurrency) while readahead is delegated to the store's
prefetch worker pool:

  - every read resolves the chunk's slice overlay (meta.read_chunk +
    build_slice) and copies the visible segments, zero-filling holes;
  - sequential access doubles a per-handle readahead window (up to
    max_readahead) and enqueues the upcoming blocks to the prefetcher,
    so the next read hits the local cache;
  - random access collapses the window, as in the reference's two-session
    heuristic (reader.go:276,370-415).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..chunk import CachedStore
from ..meta.base import BaseMeta
from ..meta.context import Context
from ..meta.slice import build_slice
from ..meta.types import CHUNK_SIZE
from ..metric.trace import global_tracer

DEFAULT_MAX_READAHEAD = 8 << 20

_TR = global_tracer()


class FileReader:
    """Read state of one open handle (reference fileReader reader.go:69)."""

    def __init__(self, dr: "DataReader", ino: int):
        self.dr = dr
        self.ino = ino
        self._lock = threading.Lock()
        self._last_end = -1
        self._ra_window = 0
        self._ra_done = 0  # readahead already enqueued up to this offset

    def read(self, ctx: Context, off: int, size: int) -> tuple[int, bytes]:
        """Returns (errno, buffer). The buffer may be a zero-copy
        memoryview into a cached block on the single-segment fast path —
        callers (fuse writev reply, fs.pread accumulation) treat it as a
        read-only bytes-like."""
        st, attr = self.dr.meta.getattr(ctx, self.ino)
        if st != 0:
            return st, b""
        length = attr.length
        # Read-your-writes: an open writer may hold a longer buffered length.
        wlen = self.dr.writer_length(self.ino)
        if wlen is not None:
            length = max(length, wlen)
        if off >= length or size <= 0:
            return 0, b""
        size = min(size, length - off)

        end = off + size
        indx, coff = divmod(off, CHUNK_SIZE)
        if coff + size <= CHUNK_SIZE:
            # fast path: the read lives in one chunk — hand its buffer
            # through without reassembly (the dominant shape: FUSE reads
            # are <=1 MiB, chunks are 64 MiB)
            st, out = self._read_chunk(indx, coff, size)
            if st != 0:
                return st, b""
        else:
            parts = []
            pos = off
            while pos < end:
                indx, coff = divmod(pos, CHUNK_SIZE)
                n = min(end - pos, CHUNK_SIZE - coff)
                st, data = self._read_chunk(indx, coff, n)
                if st != 0:
                    return st, b""
                parts.append(data)
                pos += n
            out = b"".join(parts)

        with self._lock:
            if off == self._last_end:
                self._ra_window = min(
                    self.dr.max_readahead,
                    max(self._ra_window * 2, self.dr.store.conf.block_size),
                )
            else:
                self._ra_window = 0
                self._ra_done = 0
            self._last_end = end
            window = self._ra_window
            # only plan the part of the window not already enqueued —
            # re-walking warmed blocks costs a meta read + queue churn
            # per request (reference reader.go keeps per-session state)
            ra_start = max(end, self._ra_done)
            ra_end = min(end + window, length)
            self._ra_done = max(self._ra_done, ra_end)
        if window > 0 and ra_end > ra_start:
            self._readahead(ra_start, ra_end - ra_start)
        return 0, out

    def _read_chunk(self, indx: int, coff: int, size: int) -> tuple[int, bytes]:
        st, slices = self.dr.meta.read_chunk(self.ino, indx)
        if st != 0:
            return st, b""
        view = build_slice(slices)
        end = coff + size
        segs = []  # (s0, s1, seg) overlapping non-hole segments
        for seg in view:
            s0 = max(seg.pos, coff)
            s1 = min(seg.pos + seg.len, end)
            if s0 < s1 and seg.id != 0:
                segs.append((s0, s1, seg))
        if len(segs) == 1 and segs[0][0] == coff and segs[0][1] == end:
            # one slice covers the whole request, no holes: hand the
            # store's buffer (often a zero-copy view of a cached block)
            # straight through without the assembly bytearray
            s0, s1, seg = segs[0]
            return 0, self._read_seg(seg, s0, s1)
        out = bytearray(size)
        if len(segs) > 1:
            # fragmented chunk (the pre-compaction case: many small slices
            # from overwrites): fan the per-slice loads out instead of
            # walking them serially (VERDICT r3 weak #6; reference
            # reader.go:160 runs every sliceReader as its own goroutine).
            # A dedicated pool avoids nested-submit deadlock with the
            # store's block-level download pool, which RSlice.read may
            # itself use for multi-block spans.
            ref = _TR.current_ref()  # span ref crosses the pool explicitly
            futs = [
                (s0, self.dr.spool.submit(self._read_seg, seg, s0, s1, ref))
                for s0, s1, seg in segs
            ]
            for s0, fut in futs:
                data = fut.result()
                out[s0 - coff : s0 - coff + len(data)] = data
        elif segs:
            s0, s1, seg = segs[0]
            data = self._read_seg(seg, s0, s1)
            out[s0 - coff : s0 - coff + len(data)] = data
        return 0, bytes(out)  # multi-seg/hole case: out was assembled here

    def _read_seg(self, seg, s0: int, s1: int, parent=None) -> bytes:
        rs = self.dr.store.new_reader(seg.id, seg.size)
        return rs.read(seg.off + (s0 - seg.pos), s1 - s0, parent=parent)

    def _readahead(self, off: int, size: int) -> None:
        """Warm the blocks backing [off, off+size) via the prefetch pool."""
        end = off + size
        pos = off
        while pos < end:
            indx, coff = divmod(pos, CHUNK_SIZE)
            n = min(end - pos, CHUNK_SIZE - coff)
            st, slices = self.dr.meta.read_chunk(self.ino, indx)
            if st != 0:
                return
            for seg in build_slice(slices):
                s0, s1 = max(seg.pos, coff), min(seg.pos + seg.len, coff + n)
                if s0 < s1 and seg.id != 0:
                    self.dr.store.prefetch(
                        seg.id, seg.size, seg.off + (s0 - seg.pos), s1 - s0
                    )
            pos += n


class DataReader:
    """Per-mount reader factory (reference DataReader reader.go:69-79)."""

    def __init__(
        self,
        meta: BaseMeta,
        store: CachedStore,
        max_readahead: int = DEFAULT_MAX_READAHEAD,
        writer=None,
    ):
        self.meta = meta
        self.store = store
        self.max_readahead = max_readahead
        self._writer = writer
        # slice-level fan-out for fragmented chunks on the unified
        # scheduler's "slice" lane — a separate lane from the store's
        # block-level "download" lane so nested submits cannot deadlock
        # (ISSUE 6; docs/ARCHITECTURE.md "Concurrency model")
        from ..qos import IOClass

        self.spool = store.scheduler.executor(
            "slice", IOClass.FOREGROUND, width=store.conf.max_download)

    def open(self, ino: int) -> FileReader:
        return FileReader(self, ino)

    def writer_length(self, ino: int) -> Optional[int]:
        if self._writer is None:
            return None
        return self._writer.get_length(ino)

    def close(self) -> None:
        self.spool.shutdown(wait=False)
