"""Chunk compaction: merge a fragmented slice overlay into one slice.

Reference pkg/vfs/compact.go:54 + pkg/meta/base.go:2009: read the chunk's
visible view, write it as a single new slice (zero-filling holes), then
transactionally swap the old slice list for the merged slice, decref'ing
the old slices (whose blocks get deleted when refs hit zero via the
DELETE_SLICE message). Concurrent appends during the rewrite survive: the
meta swap keeps any slices appended after the snapshot.
"""

from __future__ import annotations

from ..chunk.parallel import fetch_ordered
from ..meta.slice import build_slice
from ..meta.types import Slice
from ..qos import IOClass, scoped
from ..utils import get_logger

logger = get_logger("vfs.compact")

MIN_SLICES_TO_COMPACT = 2
# segment-read fan-out per compaction on the scheduler's "bulk" lane,
# NOT the store's download lane: RSlice.read submits block loads there
# and waits, and a bounded worker set waiting on itself deadlocks
# (docs/ARCHITECTURE.md "Concurrency model").  BACKGROUND class: the
# ambient-class demotion rule then keeps the nested block loads and the
# rewrite uploads at background priority too.
COMPACT_READ_WINDOW = 4


def compact_chunk(meta, store, ino: int, indx: int) -> bool:
    """Compact one chunk; True if a merge happened."""
    st, slices = meta.read_chunk(ino, indx)
    if st != 0 or len(slices) < MIN_SLICES_TO_COMPACT:
        return False
    snapshot = b"".join(s.encode() for s in slices)
    view = build_slice(slices)
    if not view:
        return False
    length = view[-1].pos + view[-1].len
    if length == 0:
        return False

    new_id = meta.new_slice()
    ws = store.new_writer(new_id)

    def read_seg(seg):
        if seg.id == 0:
            return b"\0" * seg.len
        return store.new_reader(seg.id, seg.size).read(seg.off, seg.len)

    window = min(COMPACT_READ_WINDOW, len(view))
    try:
        # overlap the old slices' reads; in-order yield keeps the writer
        # sequential.  A failed read is corruption here, so it raises and
        # aborts the rewrite (error policy opposite of the gc scan's).
        # scoped(BACKGROUND) demotes the nested block loads AND the
        # rewrite's uploads, which are submitted from this thread.
        with scoped(cls=IOClass.BACKGROUND), store.scheduler.executor(
            "bulk", IOClass.BACKGROUND, width=window
        ) as pool:
            for seg, data in fetch_ordered(view, read_seg, pool, window):
                ws.write_at(data, seg.pos)
            ws.finish(length)
    except Exception as e:
        logger.warning("compact ino=%d indx=%d: rewrite failed: %s", ino, indx, e)
        ws.abort()
        return False

    merged = Slice(pos=0, id=new_id, size=length, off=0, len=length)
    st = meta.compact_commit(ino, indx, snapshot, merged)
    if st != 0:
        # Lost the race to a concurrent compaction: drop our copy.
        logger.info("compact ino=%d indx=%d: conflict (%d), discarding", ino, indx, st)
        store.remove(new_id, length)
        return False
    return True


def compact_all(meta, store) -> int:
    """Compact every fragmented chunk (reference meta.CompactAll base.go:1984)."""
    n = 0
    for ino, slcs in meta.list_chunks():
        if len(slcs) >= MIN_SLICES_TO_COMPACT:
            ino_, indx = ino
            if compact_chunk(meta, store, ino_, indx):
                n += 1
    return n
