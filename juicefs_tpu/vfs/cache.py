"""Entry/attr TTL caches for the VFS (VERDICT r2 #6).

Role-match to the reference's client-side metadata caching: the kernel
caches FUSE attrs/entries for the negotiated TTLs (pkg/fuse Serve attr/
entry timeouts) and pkg/fs keeps its own entry cache for the SDK path
(pkg/fs/fs.go:130). Here one cache layer serves every adapter (FUSE,
gateway, SDK): without it each lookup/getattr is a full meta round trip —
over `redis://` that is a network RTT per stat.

Coherence contract (same as a kernel attr cache): entries expire after
the configured TTL, so another client's change becomes visible at most
TTL seconds later; this client's own mutations invalidate synchronously,
so read-your-own-writes always holds. TTL 0 disables caching entirely.
"""

from __future__ import annotations

import threading
import time
from typing import Hashable, Optional


class TTLCache:
    """Thread-safe TTL map with lazy expiry and bounded size."""

    def __init__(self, ttl: float, maxsize: int = 100_000):
        self.ttl = ttl
        self.maxsize = maxsize
        self._data: dict[Hashable, tuple[object, float]] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.ttl > 0

    def get(self, key: Hashable):
        if not self.enabled:
            return None
        with self._lock:
            item = self._data.get(key)
            if item is None:
                return None
            value, expires = item
            if time.monotonic() >= expires:
                del self._data[key]
                return None
            return value

    def put(self, key: Hashable, value) -> None:
        if not self.enabled:
            return
        with self._lock:
            if len(self._data) >= self.maxsize:
                self._sweep_locked()
            self._data[key] = (value, time.monotonic() + self.ttl)

    def invalidate(self, key: Hashable) -> None:
        with self._lock:
            self._data.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def _sweep_locked(self) -> None:
        now = time.monotonic()
        dead = [k for k, (_, exp) in self._data.items() if now >= exp]
        for k in dead:
            del self._data[k]
        if len(self._data) >= self.maxsize:  # all fresh: drop oldest half
            for k in list(self._data)[: self.maxsize // 2]:
                del self._data[k]

    def __len__(self) -> int:
        return len(self._data)


class MetaCache:
    """The VFS's attr + dentry + readdir caches with mutation hooks."""

    def __init__(self, attr_ttl: float, entry_ttl: float,
                 dir_ttl: float = 0.0):
        self.attrs = TTLCache(attr_ttl)      # ino -> Attr (as stored in meta)
        self.entries = TTLCache(entry_ttl)   # (parent, name) -> ino
        # (ino, want_attr) -> list[Entry]: full readdir snapshots
        # (reference pkg/vfs readdir cache / pkg/fs dirStream cache)
        self.dirs = TTLCache(dir_ttl, maxsize=10_000)
        # reverse index: member ino -> dir-snapshot keys holding its attr.
        # Attr-ful snapshots must honor read-your-own-writes: a hardlink/
        # chmod/write on a member invalidates every snapshot that embeds
        # its (now stale) attr — READDIRPLUS primes the kernel attr cache
        # straight from these snapshots, so staleness here would surface
        # in stat() (caught by the POSIX oracle harness).
        self._dir_members: dict[int, set] = {}
        self._members_lock = threading.Lock()
        # publication guard: a snapshot whose attrs were read BEFORE a
        # concurrent mutation must not be published AFTER it (the mutation
        # could not invalidate what was not yet registered). Callers take
        # dir_read_begin() before the meta read and hand the token to
        # put_dir, which discards the publish if any attr mutated since.
        self._mutation_gen = 0

    # -- reads -------------------------------------------------------------
    def get_attr(self, ino: int):
        return self.attrs.get(ino)

    def put_attr(self, ino: int, attr) -> None:
        self.attrs.put(ino, attr)

    def get_entry(self, parent: int, name: bytes) -> Optional[int]:
        return self.entries.get((parent, name))

    def put_entry(self, parent: int, name: bytes, ino: int) -> None:
        self.entries.put((parent, name), ino)

    # -- invalidation (local mutations) ------------------------------------
    def invalidate_attr(self, ino: int) -> None:
        self.attrs.invalidate(ino)
        self._drop_member_snapshots(ino)

    def attr_mutated(self, ino: int, attr) -> None:
        """A LOCAL mutation produced this fresh attr: cache it, but drop
        every attr-bearing dir snapshot embedding the old one
        (read-your-own-writes for READDIRPLUS/SDK listings). put_attr
        alone is for read-path refreshes, where snapshot staleness is
        within the TTL contract."""
        self.attrs.put(ino, attr)
        self._drop_member_snapshots(ino)

    def _drop_member_snapshots(self, ino: int) -> None:
        with self._members_lock:
            self._mutation_gen += 1
            keys = self._dir_members.pop(ino, None)
        if keys:
            for key in keys:
                self.dirs.invalidate(key)

    def dir_read_begin(self) -> int:
        """Token for put_dir: take BEFORE reading the listing from meta."""
        with self._members_lock:
            return self._mutation_gen

    def invalidate_entry(self, parent: int, name: bytes) -> int | None:
        """Drop one dentry; returns the ino it pointed to if cached (so the
        caller can invalidate its attr too, e.g. nlink after unlink)."""
        ino = self.entries.get((parent, name))
        self.entries.invalidate((parent, name))
        self.invalidate_dir(parent)
        return ino

    # -- readdir snapshots --------------------------------------------------
    def get_dir(self, ino: int, want_attr: bool):
        return self.dirs.get((ino, want_attr))

    def put_dir(self, ino: int, want_attr: bool, entries,
                gen: int | None = None) -> None:
        key = (ino, want_attr)
        if not (want_attr and self.dirs.enabled):
            self.dirs.put(key, entries)
            return
        # gen-check, publish, and member registration are ONE critical
        # section: a mutation between any two of them would leave a stale
        # snapshot that invalidation can never find (lock order here is
        # members_lock -> dirs lock, same as _drop_member_snapshots)
        reset = False
        with self._members_lock:
            if gen is not None and self._mutation_gen != gen:
                # an attr mutated between the meta read and here: the
                # snapshot may embed the pre-mutation attr and the
                # mutation could not invalidate it — don't publish
                return
            if len(self._dir_members) > 100_000:
                # lazily-expired snapshots leave stale rows behind;
                # resetting must OVER-invalidate: dropping the index
                # while keeping the snapshots would disconnect them
                # from mutation invalidation permanently
                self._dir_members.clear()
                reset = True
            if reset:
                self.dirs.clear()
            self.dirs.put(key, entries)
            for e in entries:
                if e.name in (b".", b".."):
                    # never registered: the kernel gets zeroed attrs
                    # for these, and indexing them would evict every
                    # child snapshot on any parent namespace change
                    continue
                self._dir_members.setdefault(e.inode, set()).add(key)

    def invalidate_dir(self, ino: int) -> None:
        self.dirs.invalidate((ino, False))
        self.dirs.invalidate((ino, True))

    def clear(self) -> None:
        self.attrs.clear()
        self.entries.clear()
        self.dirs.clear()
