"""Automatic metadata backup + background maintenance loop.

Reference pkg/vfs/backup.go:45-192 (periodic meta dump to the object
store under meta/ with rotation, interval scaled by file count) and
base.go:440's per-session cleanup goroutines (deleted-file reclaim,
stale-session GC, trash expiry). One mount runs these; the reference
elects a single winner per volume — here the election is a best-effort
object-store lock file refreshed each round.
"""

from __future__ import annotations

import gzip
import json
import threading
import time

from ..meta.context import BACKGROUND
from ..meta.dump import dump_doc
from ..meta.types import TRASH_INODE
from ..utils import get_logger

logger = get_logger("vfs.backup")

BACKUP_PREFIX = "meta/"
KEEP_BACKUPS = 7


def backup_meta(meta, storage) -> str:
    """Dump metadata, gzip it, store under meta/, rotate old backups."""
    doc = dump_doc(meta)
    payload = gzip.compress(json.dumps(doc).encode())
    key = BACKUP_PREFIX + time.strftime("dump-%Y-%m-%d-%H%M%S.json.gz", time.gmtime())
    storage.put(key, payload)
    backups = sorted(
        o.key for o in storage.list_all(BACKUP_PREFIX) if o.key.endswith(".json.gz")
    )
    for old in backups[:-KEEP_BACKUPS]:
        try:
            storage.delete(old)
        except Exception as e:
            logger.warning("rotate %s: %s", old, e)
    return key


def cleanup_trash(meta, days: float) -> int:
    """Expire trash hour-dirs older than `days` (reference base.go:2281
    CleanupTrashBefore). Returns entries removed."""
    import calendar

    st, entries = meta.readdir(BACKGROUND, TRASH_INODE)
    if st:
        return 0
    cutoff = time.time() - days * 86400
    removed = 0
    for e in entries:
        if e.name in (b".", b".."):
            continue
        try:
            ts = calendar.timegm(time.strptime(e.name.decode(), "%Y-%m-%d-%H"))
        except ValueError:
            continue
        if ts + 3600 < cutoff:
            st, n = meta.remove_recursive(
                BACKGROUND, TRASH_INODE, e.name, skip_trash=True
            )
            removed += n
    return removed


class BackgroundJobs:
    """Per-mount maintenance loop (reference base.go:440 refreshSession's
    bgjob half + initBackgroundTasks cmd/mount.go:357)."""

    def __init__(self, meta, store, interval: float = 60.0,
                 backup_interval: float = 3600.0):
        self.meta = meta
        self.store = store
        self.interval = interval
        self.backup_interval = backup_interval
        self._stop = threading.Event()
        self._last_backup = 0.0
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="vfs-bgjobs"
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)

    def _elect(self) -> bool:
        """Best-effort single-winner election via a lease object."""
        key = "meta/bgjob-lease"
        now = time.time()
        try:
            raw = bytes(self.store.storage.get(key))
            holder = json.loads(raw)
            if holder["sid"] != self.meta.sid and now - holder["ts"] < 5 * self.interval:
                return False
        except Exception:
            pass
        try:
            self.store.storage.put(
                key, json.dumps({"sid": self.meta.sid, "ts": now}).encode()
            )
            return True
        except Exception:
            return False

    def run_once(self) -> dict:
        stats = {}
        try:
            stats["deleted_files"] = self.meta.cleanup_deleted_files()
        except Exception as e:
            logger.warning("cleanup deleted files: %s", e)
        try:
            stats["stale_sessions"] = self.meta.clean_stale_sessions()
        except Exception as e:
            logger.warning("clean stale sessions: %s", e)
        try:
            days = self.meta.fmt.trash_days
            if days > 0:
                stats["trash_expired"] = cleanup_trash(self.meta, days)
        except Exception as e:
            logger.warning("trash cleanup: %s", e)
        now = time.time()
        if now - self._last_backup >= self.backup_interval:
            try:
                stats["backup"] = backup_meta(self.meta, self.store.storage)
                self._last_backup = now
            except Exception as e:
                logger.warning("meta backup: %s", e)
        return stats

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if self._elect():
                self.run_once()
