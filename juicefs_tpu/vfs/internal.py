"""Internal virtual files (reference pkg/vfs/internal.go:78-105).

Six virtual inodes live at the volume root, invisible to readdir:

  .control    write a JSON command, read back streamed JSON result
              (reference writes binary op+args and reads progress
              frames, internal.go:294 handleInternalMsg — same protocol
              role, JSON encoding). Ops: info, summary, rmr, warmup,
              compact, clone.
  .accesslog  live op trace; lines materialize only while open
  .trace      live span-event stream (JSON lines, metric/trace.py);
              spans materialize only while open, like .accesslog
  .stats      point-in-time Prometheus text dump of the registry
  .config     the volume's runtime VFSConfig + Format as JSON
  .status     object-plane health JSON: breaker state / degradation
              ladder rung, retry/hedge/abandon counters, staging backlog
              (object/resilient.py; surfaced by `juicefs status`)

Inode numbers sit at the top of the 31-bit space like the reference's
(internal.go MinInternalNode), far above allocated inodes.
"""

from __future__ import annotations

import errno as _errno
import json
import time

from ..meta.context import Context
from ..meta.types import Attr, TYPE_FILE
from ..metric.trace import global_tracer

CONTROL_INO = 0x7FFFFFFF
LOG_INO = 0x7FFFFFFE
STATS_INO = 0x7FFFFFFD
CONFIG_INO = 0x7FFFFFFC
TRACE_INO = 0x7FFFFFFB
STATUS_INO = 0x7FFFFFFA
MIN_INTERNAL_INO = STATUS_INO

INTERNAL_NAMES = {
    b".control": CONTROL_INO,
    b".accesslog": LOG_INO,
    b".stats": STATS_INO,
    b".config": CONFIG_INO,
    b".trace": TRACE_INO,
    b".status": STATUS_INO,
}


# Advertised length of the virtual files. The reference reports 0 and
# relies on FOPEN_DIRECT_IO to keep the kernel reading past "EOF", but
# some kernels (gVisor-style 4.4 emulation) ignore the flag and clamp
# reads at i_size — making every virtual file read empty. A modest fake
# length keeps both behaviors working: direct-io kernels ignore it,
# clamping kernels keep issuing reads (a stream reader there gets at most
# this many bytes per open). Kept small enough that a buffered read()
# sizing its buffer from st_size stays cheap.
STREAM_LENGTH = 4 << 20


def internal_attr(ino: int) -> Attr:
    now = int(time.time())
    return Attr(
        typ=TYPE_FILE, mode=0o400 if ino != CONTROL_INO else 0o600,
        uid=0, gid=0, nlink=1, length=STREAM_LENGTH,
        atime=now, mtime=now, ctime=now, full=True,
    )


def is_internal(ino: int) -> bool:
    return ino >= MIN_INTERNAL_INO


class ControlHandler:
    """Executes .control commands against the live mount
    (reference internal.go handleInternalMsg; consumed by info/rmr/
    warmup/compact/clone CLIs through the mounted fs)."""

    def __init__(self, vfs):
        self.vfs = vfs

    def handle(self, ctx: Context, cmd: dict) -> dict:
        op = cmd.get("op", "")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            return {"errno": _errno.EINVAL, "error": f"unknown op {op!r}"}
        try:
            return fn(ctx, cmd)
        except Exception as e:  # never kill the mount from a control op
            return {"errno": _errno.EIO, "error": str(e)}

    def _op_info(self, ctx, cmd):
        ino = int(cmd["inode"])
        st, attr = self.vfs.meta.getattr(ctx, ino)
        if st:
            return {"errno": st}
        out = {
            "errno": 0, "inode": ino, "type": attr.typ, "length": attr.length,
            "nlink": attr.nlink, "paths": self.vfs.meta.get_paths(ino),
        }
        if attr.typ == TYPE_FILE:
            from ..meta.types import CHUNK_SIZE

            chunks = []
            for indx in range((attr.length + CHUNK_SIZE - 1) // CHUNK_SIZE):
                st, slices = self.vfs.meta.read_chunk(ino, indx)
                if st == 0:
                    chunks.append([
                        [s.pos, s.id, s.size, s.off, s.len] for s in slices
                    ])
            out["chunks"] = chunks
        return out

    def _op_summary(self, ctx, cmd):
        st, s = self.vfs.meta.summary(ctx, int(cmd["inode"]))
        if st:
            return {"errno": st}
        return {"errno": 0, "files": s.files, "dirs": s.dirs,
                "length": s.length, "size": s.size}

    def _op_rmr(self, ctx, cmd):
        st, removed = self.vfs.meta.remove_recursive(
            ctx, int(cmd["parent"]), cmd["name"].encode(),
            skip_trash=bool(cmd.get("skip_trash")),
        )
        # bulk namespace change bypassed the per-op invalidation hooks
        self.vfs.cache.clear()
        return {"errno": st, "removed": removed}

    def _op_warmup(self, ctx, cmd):
        from ..meta.types import CHUNK_SIZE

        ino = int(cmd["inode"])
        st, attr = self.vfs.meta.getattr(ctx, ino)
        if st:
            return {"errno": st}
        slices = 0
        for indx in range((attr.length + CHUNK_SIZE - 1) // CHUNK_SIZE):
            st, slcs = self.vfs.meta.read_chunk(ino, indx)
            for s in slcs:
                if s.id:
                    self.vfs.store.fill_cache(s.id, s.size)
                    slices += 1
        return {"errno": 0, "slices": slices}

    def _op_compact(self, ctx, cmd):
        from ..meta.types import CHUNK_SIZE
        from .compact import compact_chunk

        ino = int(cmd["inode"])
        st, attr = self.vfs.meta.getattr(ctx, ino)
        if st:
            return {"errno": st}
        done = 0
        for indx in range((attr.length + CHUNK_SIZE - 1) // CHUNK_SIZE):
            if compact_chunk(self.vfs.meta, self.vfs.store, ino, indx):
                done += 1
        return {"errno": 0, "compacted": done}

    def _op_epoch_plan(self, ctx, cmd):
        """Dataset-manifest epoch hint (ISSUE 13 satellite): the training
        loader knows its exact shard order for the next epoch, so it
        hands the reader's sequential-EOF hook a precise next-shard plan
        instead of the name-order readdir guess (ISSUE 11 residual).

            {"op": "epoch_plan", "dir": <dir inode>,
             "shards": ["shard-007", "shard-002", ...]}   # epoch order

        Each shard's EOF then warms its successor in THIS list (the last
        wraps to the first — the next epoch's opening shard).  An empty
        list clears the plan and restores the readdir guess."""
        names = [n.encode() if isinstance(n, str) else bytes(n)
                 for n in cmd.get("shards", [])]
        if not names:
            self.vfs.reader.set_epoch_plan({})
            return {"errno": 0, "planned": 0}
        dir_ino = int(cmd.get("dir", 1))
        inos = []
        for nm in names:
            st, ino, _ = self.vfs.meta.lookup(ctx, dir_ino, nm)
            if st:
                return {"errno": st,
                        "error": f"shard {nm.decode(errors='replace')!r} "
                                 "not found"}
            inos.append(ino)
        plan = {inos[i]: inos[(i + 1) % len(inos)] for i in range(len(inos))}
        self.vfs.reader.set_epoch_plan(plan)
        return {"errno": 0, "planned": len(plan)}

    def _op_clone(self, ctx, cmd):
        if not hasattr(self.vfs.meta, "clone"):
            return {"errno": _errno.ENOSYS}
        st, new_ino = self.vfs.meta.clone(
            ctx, int(cmd["inode"]), int(cmd["parent"]), cmd["name"].encode()
        )
        if st == 0:
            self.vfs.cache.invalidate_attr(int(cmd["parent"]))
        return {"errno": st, "inode": new_ino}


class InternalFiles:
    """Open-handle state for the virtual files."""

    def __init__(self, vfs):
        self.vfs = vfs
        self.control = ControlHandler(vfs)
        self._bufs: dict[int, bytes] = {}  # fh -> pending read data

    def lookup(self, name: bytes):
        ino = INTERNAL_NAMES.get(name)
        if ino is None:
            return None
        return ino, internal_attr(ino)

    def open(self, ino: int, fh: int) -> None:
        if ino == LOG_INO:
            self.vfs.accesslog.open_reader(fh)
        elif ino == TRACE_INO:
            # the tracer is process-global: key the reader by this mount
            # too, so two mounts' fh counters cannot collide
            global_tracer().open_reader((id(self), fh))
        elif ino == STATS_INO:
            from ..metric import global_registry

            self._bufs[fh] = global_registry().render().encode()
        elif ino == CONFIG_INO:
            conf = {
                "readonly": self.vfs.conf.readonly,
                "max_readahead": self.vfs.conf.max_readahead,
                "attr_timeout": self.vfs.conf.attr_timeout,
            }
            if self.vfs.fmt is not None:
                conf["format"] = json.loads(self.vfs.fmt.remove_secret().to_json())
            self._bufs[fh] = json.dumps(conf, indent=2).encode()
        elif ino == STATUS_INO:
            self._bufs[fh] = json.dumps(self._status_payload(), indent=2,
                                        default=str).encode()
        else:
            self._bufs[fh] = b""

    def _status_payload(self) -> dict:
        """Object-plane health for `.status` / `juicefs status`: which
        ladder rung the mount is on, breaker state, resilience activity,
        and the writeback/degraded staging backlog."""
        from ..object.resilient import resilience_snapshot

        from ..chunk.cached_store import _staged_len

        store = self.vfs.store
        health = getattr(store.storage, "health", None)
        with store._pending_lock:
            staged_blocks = len(store._pending_staged)
            # entries past the RAM cap are spilled refs, not bytes
            staged_bytes = sum(_staged_len(v)
                               for v in store._pending_staged.values())
            staged_mem = store._staged_mem
        out = {
            "object_plane": health() if callable(health) else {
                "resilient": False},
            "degraded": bool(getattr(store, "degraded", False)),
            "staging": {"blocks": staged_blocks, "bytes": staged_bytes,
                        "mem_bytes": staged_mem},
            "resilience_counters": resilience_snapshot(),
        }
        group = getattr(store, "cache_group", None)
        if group is not None:
            # ring membership + per-peer breaker state (ISSUE 4: a dead
            # peer's open breaker must be observable here)
            out["cache_group"] = group.health()
        # epoch-streaming read path (ISSUE 11): live window/streaming
        # state plus the prefetch used/issued effectiveness counters
        reader = getattr(self.vfs, "reader", None)
        if reader is not None:
            out["readahead"] = reader.stats()
        # checkpoint write plane (ISSUE 13): group-commit batching state —
        # queue depth, drains vs batched mutations, sticky deferred errors
        meta = getattr(self.vfs, "meta", None)
        wb = getattr(meta, "wbatch", None)
        if wb is not None:
            out["wbatch"] = wb.stats()
        # meta-plane fault contract (ISSUE 14): breaker state + probe
        # age, stale-served count, replica role — the meta twin of the
        # object_plane snapshot above (a blackout must be OBSERVABLE
        # here, not just inferable from EIOs)
        res = getattr(meta, "resilience", None)
        if res is not None:
            mp = res.health()
            if mp.get("enabled"):
                mp["lease"] = meta.lease.stats()
                mp["session"] = {"sid": meta.sid,
                                 "beat_failures": meta._beat_failures}
            out["meta_plane"] = mp
        # gateway serving plane (ISSUE 15): admission gate occupancy,
        # shed count, per-tenant request rates, streaming-buffer bounds —
        # present only when a gateway adapter serves this vfs
        try:
            from ..gateway.serve import status_for

            gw = status_for(self.vfs)
            if gw is not None:
                out["gateway"] = gw
        except Exception:
            pass  # a torn-down adapter must never break a status read
        # unified I/O scheduler + bandwidth budget (ISSUE 6): lane/queue
        # occupancy per class and token-bucket levels
        sched = getattr(store, "scheduler", None)
        if sched is not None:
            out["qos"] = sched.snapshot()
        limiter = getattr(store, "limiter", None)
        if limiter is not None:
            out.setdefault("qos", {})["limiter"] = limiter.snapshot()
        return out

    def read(self, ino: int, fh: int, off: int, size: int) -> tuple[int, bytes]:
        if ino == LOG_INO:
            return 0, self.vfs.accesslog.read(fh, size)
        if ino == TRACE_INO:
            return 0, global_tracer().read((id(self), fh), size)
        buf = self._bufs.get(fh, b"")
        return 0, buf[off : off + size]

    def write(self, ctx: Context, ino: int, fh: int, data: bytes) -> int:
        if ino != CONTROL_INO:
            return _errno.EACCES
        try:
            # bytes() first: the FUSE path delivers memoryviews, which
            # json.loads rejects with TypeError
            cmd = json.loads(bytes(data))
        except (ValueError, TypeError):
            return _errno.EINVAL
        result = self.control.handle(ctx, cmd)
        self._bufs[fh] = json.dumps(result).encode()
        return 0

    def release(self, ino: int, fh: int) -> None:
        if ino == LOG_INO:
            self.vfs.accesslog.close_reader(fh)
        elif ino == TRACE_INO:
            global_tracer().close_reader((id(self), fh))
        self._bufs.pop(fh, None)
