"""Open-handle table (reference: pkg/vfs/handle.go:32-263).

A handle binds a kernel file descriptor to per-open state: flags, the
FileReader/FileWriter pair for regular files, a readdir snapshot for
directories, and reader/writer op accounting used to serialize flushes
against in-flight reads/writes. POSIX/BSD lock owners hang off the handle
too (lock state itself lives in the meta engine so it is cluster-wide).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..meta.types import Entry


class Handle:
    def __init__(self, fh: int, ino: int, flags: int = 0):
        self.fh = fh
        self.ino = ino
        self.flags = flags
        self.reader = None  # FileReader
        self.writer = None  # FileWriter
        self.children: Optional[list[Entry]] = None  # readdir snapshot
        self.read_off = 0  # last sequential read end (readdir offset cache)
        self.lock_owner = 0
        self._cond = threading.Condition()
        self._readers = 0
        self._writers = 0

    # Op accounting: flush must wait out in-flight data ops on this handle
    # (reference handle.go Rlock/Wlock with interruptible wait).
    def begin_read(self) -> None:
        with self._cond:
            self._readers += 1

    def end_read(self) -> None:
        with self._cond:
            self._readers -= 1
            self._cond.notify_all()

    def begin_write(self) -> None:
        with self._cond:
            self._writers += 1

    def end_write(self) -> None:
        with self._cond:
            self._writers -= 1
            self._cond.notify_all()

    def wait_quiet(self, timeout: float = 30.0) -> bool:
        """Wait until no data op is in flight (for flush/release)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._readers == 0 and self._writers == 0, timeout
            )


class HandleTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._next = 1
        self._handles: dict[int, Handle] = {}
        self._by_ino: dict[int, list[Handle]] = {}

    def new(self, ino: int, flags: int = 0) -> Handle:
        with self._lock:
            fh = self._next
            self._next += 1
            h = Handle(fh, ino, flags)
            self._handles[fh] = h
            self._by_ino.setdefault(ino, []).append(h)
            return h

    def insert(self, fh: int, ino: int, flags: int = 0) -> Handle:
        """Recreate a handle with a FIXED fh — seamless-upgrade restore
        (reference handle.go:312-415): the kernel keeps using the fh
        numbers the predecessor issued."""
        with self._lock:
            h = Handle(fh, ino, flags)
            self._handles[fh] = h
            self._by_ino.setdefault(ino, []).append(h)
            if fh >= self._next:
                self._next = fh + 1
            return h

    def get(self, fh: int) -> Optional[Handle]:
        with self._lock:
            return self._handles.get(fh)

    def of_ino(self, ino: int) -> list[Handle]:
        with self._lock:
            return list(self._by_ino.get(ino, ()))

    def remove(self, fh: int) -> Optional[Handle]:
        with self._lock:
            h = self._handles.pop(fh, None)
            if h is not None:
                lst = self._by_ino.get(h.ino, [])
                if h in lst:
                    lst.remove(h)
                if not lst:
                    self._by_ino.pop(h.ino, None)
            return h

    def count(self) -> int:
        with self._lock:
            return len(self._handles)

    def all(self) -> list[Handle]:
        with self._lock:
            return list(self._handles.values())
