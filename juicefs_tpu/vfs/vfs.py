"""VFS core: the filesystem every presentation adapter serves.

Port of the reference's pkg/vfs/vfs.go surface (vfs.go:155-1157): FUSE, the
S3 gateway, WebDAV, and the SDK all call these methods. Namespace/attr ops
delegate to the metadata engine; file data flows through DataReader /
DataWriter over the chunk store; the handle table binds kernel fds to open
state. Key consistency behaviors preserved from the reference:

  - reads flush overlapping buffered writes first (vfs.go:651 Read calls
    writer flush), so a process always reads its own writes;
  - truncate/fallocate flush the target file before mutating length
    (vfs.go:867-947), and open writers learn the new length;
  - O_APPEND writes land at the current (buffered) end of file;
  - release waits out in-flight ops, flushes, then drops the handle.
"""

from __future__ import annotations

import errno as _errno
import os
import threading
from dataclasses import dataclass, field, replace

from ..chunk import CachedStore
from ..meta.base import BaseMeta
from ..meta.context import Context
from ..meta.types import (
    Attr,
    CHUNK_SIZE,
    Entry,
    Format,
    SET_ATTR_SIZE,
    TYPE_DIRECTORY,
    TYPE_FILE,
)
from ..metric import global_registry
from ..qos import tenant_scope
from ..utils import get_logger
from .accesslog import AccessLogger
from .cache import MetaCache
from .handles import Handle, HandleTable
from .internal import INTERNAL_NAMES, InternalFiles, internal_attr, is_internal
from .reader import DataReader
from .writer import DataWriter

logger = get_logger("vfs")

ROOT_INO = 1
MAX_FILE_SIZE = CHUNK_SIZE << 31  # cap file length like the reference
MAX_SYMLINK = 4096


@dataclass
class VFSConfig:
    readonly: bool = False
    max_readahead: int = 8 << 20
    # epoch-streaming read path (ISSUE 11): a handle sustaining
    # sequential progress past `streaming_after` bytes escalates from the
    # block-granularity window doubler to file-granularity readahead
    # capped at `max_streaming` (further bounded by the prefetch queue)
    streaming_read: bool = True
    streaming_after: int = 16 << 20
    max_streaming: int = 64 << 20
    attr_timeout: float = 1.0
    entry_timeout: float = 1.0
    dir_entry_timeout: float = 1.0
    hide_internal: bool = False
    extra: dict = field(default_factory=dict)


class VFS:
    def __init__(
        self,
        meta: BaseMeta,
        store: CachedStore,
        conf: VFSConfig | None = None,
        fmt: Format | None = None,
    ):
        self.meta = meta
        self.store = store
        self.conf = conf or VFSConfig()
        self.fmt = fmt
        self.handles = HandleTable()
        self.writer = DataWriter(meta, store)
        self.reader = DataReader(
            meta, store, self.conf.max_readahead, writer=self.writer,
            streaming=self.conf.streaming_read,
            streaming_after=self.conf.streaming_after,
            max_streaming=self.conf.max_streaming,
        )
        self._append_lock = threading.Lock()
        # entry/attr TTL caches (vfs/cache.py): kernel-style caching for
        # every adapter; local mutations invalidate synchronously below
        self.cache = MetaCache(self.conf.attr_timeout, self.conf.entry_timeout,
                               self.conf.dir_entry_timeout)
        # push invalidation (VERDICT r3 #4): peers' changes arrive via the
        # session refresher well inside the TTLs; the FUSE server attaches
        # itself as kernel_notifier so the dcache is poked too
        self.kernel_notifier = None
        if hasattr(meta, "on_invalidate"):
            meta.on_invalidate(self._remote_invalidate)
        self.accesslog = AccessLogger()
        self.internal = InternalFiles(self)
        self._op_hist = global_registry().histogram(
            "juicefs_fuse_ops_durations_histogram_seconds",
            "Operation latencies (reference vfs/accesslog.go:30-46)",
            ("method",),
        )
        # memory accounting (reference vfs.go:1276-1315 buffer gauges +
        # pkg/utils/alloc.go): scraped via /metrics and `juicefs stats`
        reg = global_registry()
        reg.gauge(
            "juicefs_used_buffer_size_bytes",
            "Bytes in un-uploaded write buffers",
        ).set_function(self.writer.buffered_bytes)
        reg.gauge(
            "juicefs_blockcache_bytes", "Bytes in the local block cache"
        ).set_function(lambda: self.store.cache.stats()[1])
        reg.gauge(
            "juicefs_blockcache_blocks", "Blocks in the local block cache"
        ).set_function(lambda: self.store.cache.stats()[0])
        reg.gauge(
            "juicefs_index_dropped_blocks",
            "Blocks skipped by the content indexer under overload "
            "(advisory index; gc --dedup backfills)",
        ).set_function(
            lambda: self.store.indexer.dropped if self.store.indexer else 0
        )
        self._instrument()

    def _instrument(self) -> None:
        """Wrap public ops with latency metrics + access logging + vfs-layer
        spans (reference: every VFS method logit()s, accesslog.go:64). Ops
        on the internal virtual files are never logged or traced — they
        would feed the very stream being read."""
        import time as _time

        from ..metric.trace import NULL_SPAN, global_tracer

        self._op_depth = threading.local()
        tracer = global_tracer()

        for name in (
            "lookup", "getattr", "setattr", "mknod", "mkdir", "unlink",
            "rmdir", "rename", "link", "symlink", "readdir", "create",
            "open", "read", "write", "flush", "fsync", "release",
            "truncate_ino", "copy_file_range", "statfs",
        ):
            orig = getattr(self, name)
            op_hist = self._op_hist.labels(name)

            def wrapper(ctx, *a, __orig=orig, __name=name, __hist=op_hist, **kw):
                # Only the outermost op records: fsync->flush and
                # O_APPEND-write->getattr are internal self-calls, not
                # kernel requests (one log line per VFS op, like the
                # reference).
                if getattr(self._op_depth, "d", 0) > 0:
                    return __orig(ctx, *a, **kw)
                internal = (
                    bool(a) and isinstance(a[0], int) and is_internal(a[0])
                )
                sp = NULL_SPAN if internal else tracer.span("vfs", __name)
                self._op_depth.d = 1
                t0 = _time.perf_counter()
                # tenant tagging of meta ops (ISSUE 9): EVERY vfs op runs
                # under the request uid's tenant scope, so the per-tenant
                # meta-op limiter and the DRR fairness queues attribute
                # lookups/getattrs — not just block I/O — to the real user
                with sp, tenant_scope(getattr(ctx, "uid", 0)):
                    try:
                        out = __orig(ctx, *a, **kw)
                    finally:
                        self._op_depth.d = 0
                        dur = _time.perf_counter() - t0
                        __hist.observe(dur)
                    err = out[0] if isinstance(out, tuple) else out
                    if not isinstance(err, int):
                        err = 0
                    if sp.active:
                        sp.set(
                            ino=a[0] if a and isinstance(a[0], int) else 0,
                            errno=err,
                        )
                    if self.accesslog.active and not internal:
                        args = ",".join(
                            str(x) for x in a[:3] if isinstance(x, (int, bytes, str))
                        )
                        self.accesslog.logit(
                            __name, args, err, dur,
                            pid=getattr(ctx, "pid", 0),
                            uid=getattr(ctx, "uid", 0),
                            gid=getattr(ctx, "gid", 0),
                        )
                return out

            setattr(self, name, wrapper)

    # -- namespace ---------------------------------------------------------

    def lookup(self, ctx: Context, parent: int, name: bytes) -> tuple[int, int, Attr]:
        if parent == ROOT_INO and name in INTERNAL_NAMES:
            ino, attr = self.internal.lookup(name)
            return 0, ino, attr
        # "." / ".." resolve relative to a directory whose parentage can
        # change under rename with no (parent, name) key to invalidate —
        # never cache them.
        cacheable = name not in (b".", b"..")
        if cacheable:
            ino = self.cache.get_entry(parent, name)
            if ino is not None:
                attr = self.cache.get_attr(ino)
                if attr is not None:
                    # The dentry is shared across users, so the parent
                    # execute-permission check meta.lookup would do must
                    # still run per-caller (cached parent attr avoids the
                    # round trip on warm walks).
                    from ..meta.base import MODE_MASK_X

                    st = self.meta.access(
                        ctx, parent, MODE_MASK_X, self.cache.get_attr(parent)
                    )
                    if st != 0:
                        return st, 0, Attr()
                    return 0, ino, self._overlay_length(ino, attr)
        st, ino, attr = self.meta.lookup(ctx, parent, name)
        if st == 0:
            if cacheable:
                self.cache.put_entry(parent, name, ino)
                self.cache.put_attr(ino, attr)
            attr = self._overlay_length(ino, attr)
        return st, ino, attr

    def _overlay_length(self, ino: int, attr: Attr) -> Attr:
        """Surface buffered writes in stat (reference UpdateLength). Copy
        first: the attr may be a cached instance (meta openfile cache or
        our TTL cache) and mutating it would poison the cache."""
        if attr.typ == TYPE_FILE:
            wlen = self.writer.get_length(ino)
            if wlen is not None and wlen > attr.length:
                attr = replace(attr)
                attr.length = wlen
        return attr

    def getattr(self, ctx: Context, ino: int) -> tuple[int, Attr]:
        if is_internal(ino):
            return 0, internal_attr(ino)
        attr = self.cache.get_attr(ino)
        if attr is not None:
            return 0, self._overlay_length(ino, attr)
        st, attr = self.meta.getattr(ctx, ino)
        if st == 0:
            self.cache.put_attr(ino, attr)
            attr = self._overlay_length(ino, attr)
        return st, attr

    def setattr(self, ctx: Context, ino: int, flags: int, attr: Attr) -> tuple[int, Attr]:
        if self.conf.readonly:
            return _errno.EROFS, Attr()
        if flags & SET_ATTR_SIZE:
            if attr.length > MAX_FILE_SIZE:
                return _errno.EFBIG, Attr()
            st = self.writer.flush(ino)
            if st != 0:
                return st, Attr()
        st, out = self.meta.setattr(ctx, ino, flags, attr)
        if st == 0:
            self.cache.attr_mutated(ino, out)
            if flags & SET_ATTR_SIZE:
                self.writer.truncate(ino, out.length)
        return st, out

    def _remote_invalidate(self, events: list[tuple]) -> None:
        """Another client changed these: drop TTL caches now (instead of
        waiting out the TTL) and poke the kernel's attr/page/dcache
        (reference pkg/vfs/vfs.go:1228 invalidation callbacks)."""
        kn = self.kernel_notifier
        for ev in events:
            if ev[0] == "a":
                ino = ev[1]
                self.cache.invalidate_attr(ino)
                self.cache.invalidate_dir(ino)
                if kn is not None:
                    try:
                        kn.notify_inval_inode(ino)
                    except Exception:
                        pass
            elif ev[0] == "e":
                parent, name = ev[1], ev[2]
                self.cache.invalidate_entry(parent, name)
                if kn is not None:
                    try:
                        kn.notify_inval_entry(parent, name)
                    except Exception:
                        pass

    def _entry_created(self, parent: int, name: bytes, ino: int, attr: Attr) -> None:
        """Cache bookkeeping after a successful namespace insert: the new
        dentry/attr are known exactly; the parent's attr (mtime, nlink for
        mkdir) changed in meta, so drop it."""
        self.cache.invalidate_attr(parent)
        self.cache.invalidate_dir(parent)
        self.cache.put_entry(parent, name, ino)
        # mutation-grade: a hardlink target's nlink changed in EVERY
        # directory snapshot that embeds it, not just the new parent's
        self.cache.attr_mutated(ino, attr)

    def _entry_removed(self, parent: int, name: bytes) -> None:
        ino = self.cache.invalidate_entry(parent, name)
        self.cache.invalidate_attr(parent)
        if ino is not None:
            self.cache.invalidate_attr(ino)  # nlink/ctime changed

    def mknod(self, ctx, parent, name, mode, cumask=0, rdev=0) -> tuple[int, int, Attr]:
        if self.conf.readonly:
            return _errno.EROFS, 0, Attr()
        st, ino, attr = self.meta.mknod(ctx, parent, name, TYPE_FILE, mode, cumask, rdev)
        if st == 0:
            self._entry_created(parent, name, ino, attr)
        return st, ino, attr

    def mkdir(self, ctx, parent, name, mode, cumask=0) -> tuple[int, int, Attr]:
        if self.conf.readonly:
            return _errno.EROFS, 0, Attr()
        st, ino, attr = self.meta.mkdir(ctx, parent, name, mode, cumask)
        if st == 0:
            self._entry_created(parent, name, ino, attr)
        return st, ino, attr

    def symlink(self, ctx, parent, name, target: bytes) -> tuple[int, int, Attr]:
        if self.conf.readonly:
            return _errno.EROFS, 0, Attr()
        if len(target) >= MAX_SYMLINK:
            return _errno.ENAMETOOLONG, 0, Attr()
        st, ino, attr = self.meta.symlink(ctx, parent, name, target)
        if st == 0:
            self._entry_created(parent, name, ino, attr)
        return st, ino, attr

    def readlink(self, ctx, ino) -> tuple[int, bytes]:
        return self.meta.readlink(ctx, ino)

    def unlink(self, ctx, parent, name) -> int:
        if self.conf.readonly:
            return _errno.EROFS
        st = self.meta.unlink(ctx, parent, name)
        if st == 0:
            self._entry_removed(parent, name)
        return st

    def rmdir(self, ctx, parent, name) -> int:
        if self.conf.readonly:
            return _errno.EROFS
        st = self.meta.rmdir(ctx, parent, name)
        if st == 0:
            self._entry_removed(parent, name)
        return st

    def rename(self, ctx, psrc, nsrc, pdst, ndst, flags=0) -> tuple[int, int, Attr]:
        if self.conf.readonly:
            return _errno.EROFS, 0, Attr()
        st, ino, attr = self.meta.rename(ctx, psrc, nsrc, pdst, ndst, flags)
        if st == 0:
            self._entry_removed(psrc, nsrc)
            self._entry_removed(pdst, ndst)  # replaced target (if any)
            if not flags:  # EXCHANGE/WHITEOUT: leave both uncached
                self.cache.put_entry(pdst, ndst, ino)
                self.cache.put_attr(ino, attr)
        return st, ino, attr

    def link(self, ctx, ino, parent, name) -> tuple[int, Attr]:
        if self.conf.readonly:
            return _errno.EROFS, Attr()
        st = self.writer.flush(ino)
        if st != 0:
            return st, Attr()
        st, attr = self.meta.link(ctx, ino, parent, name)
        if st == 0:
            self._entry_created(parent, name, ino, attr)
        return st, attr

    # -- directories -------------------------------------------------------

    def opendir(self, ctx: Context, ino: int) -> tuple[int, int]:
        st, attr = self.meta.getattr(ctx, ino)
        if st != 0:
            return st, 0
        if attr.typ != TYPE_DIRECTORY:
            return _errno.ENOTDIR, 0
        h = self.handles.new(ino)
        return 0, h.fh

    def readdir(
        self, ctx: Context, ino: int, fh: int, offset: int, want_attr: bool = False
    ) -> tuple[int, list[Entry]]:
        h = self.handles.get(fh)
        if h is None:
            return _errno.EBADF, []
        if h.children is None or offset == 0:
            entries = self.cache.get_dir(ino, want_attr)
            if entries is not None:
                # snapshot is shared across users: re-check this caller's
                # read permission (same rule as cached lookups)
                st = self.meta.access(ctx, ino, 4, self.cache.get_attr(ino))
                if st != 0:
                    return st, []
            else:
                gen = self.cache.dir_read_begin()
                st, entries = self.meta.readdir(ctx, ino, want_attr)
                if st != 0:
                    return st, []
                self.cache.put_dir(ino, want_attr, entries, gen=gen)
            h.children = entries
        return 0, h.children[offset:]

    def releasedir(self, ctx: Context, fh: int) -> int:
        self.handles.remove(fh)
        return 0

    # -- files -------------------------------------------------------------

    def create(
        self, ctx: Context, parent: int, name: bytes, mode: int, cumask: int = 0,
        flags: int = os.O_RDWR,
    ) -> tuple[int, int, Attr, int]:
        if self.conf.readonly:
            return _errno.EROFS, 0, Attr(), 0
        st, ino, attr = self.meta.create(ctx, parent, name, mode, cumask, flags)
        if st != 0:
            return st, 0, Attr(), 0
        self._entry_created(parent, name, ino, attr)
        fh = self._new_file_handle(ino, attr.length, flags)
        return 0, ino, attr, fh

    def open(self, ctx: Context, ino: int, flags: int) -> tuple[int, Attr, int]:
        if is_internal(ino):
            h = self.handles.new(ino, flags)
            self.internal.open(ino, h.fh)
            return 0, internal_attr(ino), h.fh
        accmode = flags & os.O_ACCMODE
        if self.conf.readonly and (
            accmode != os.O_RDONLY or flags & (os.O_TRUNC | os.O_APPEND)
        ):
            return _errno.EROFS, Attr(), 0
        st, attr = self.meta.open(ctx, ino, flags)
        if st != 0:
            return st, Attr(), 0
        if flags & os.O_TRUNC:
            st, attr = self.truncate_ino(ctx, ino, 0)
            if st != 0:
                self.meta.close(ctx, ino)
                return st, Attr(), 0
        fh = self._new_file_handle(ino, attr.length, flags)
        return 0, attr, fh

    # With the kernel writeback cache the kernel issues READs on handles
    # the app opened O_WRONLY (read-modify-write of partial pages); the
    # FUSE server sets this so such handles carry a reader too.
    always_readable_handles = False

    def _new_file_handle(self, ino: int, length: int, flags: int) -> int:
        h = self.handles.new(ino, flags)
        accmode = flags & os.O_ACCMODE
        if accmode in (os.O_RDONLY, os.O_RDWR) or self.always_readable_handles:
            h.reader = self.reader.open(ino)
        if accmode in (os.O_WRONLY, os.O_RDWR):
            h.writer = self.writer.open(ino, length)
        return h.fh

    def read(self, ctx: Context, ino: int, fh: int, off: int, size: int) -> tuple[int, bytes]:
        h = self.handles.get(fh)
        if h is None or h.ino != ino:
            return _errno.EBADF, b""
        if is_internal(ino):
            return self.internal.read(ino, fh, off, size)
        if h.reader is None:
            return _errno.EACCES, b""
        if off >= MAX_FILE_SIZE or size > (64 << 20):
            return _errno.EFBIG, b""
        # Read-after-write consistency: push buffered writes down first,
        # but only when they overlap the read range (avoids slice churn
        # in interleaved write/read workloads).
        fw = self.writer.find(ino)
        if fw is not None:
            st = fw.flush_if_overlaps(off, size)
            if st != 0:
                return st, b""
        h.begin_read()
        try:
            # per-tenant fair queueing (ISSUE 6): block I/O this read fans
            # out is DRR-queued under the requesting uid, so one user
            # flooding reads cannot monopolize the foreground class
            with tenant_scope(ctx.uid):
                return h.reader.read(ctx, off, size)
        finally:
            h.end_read()

    def write(self, ctx: Context, ino: int, fh: int, off: int, data: bytes) -> int:
        h = self.handles.get(fh)
        if h is None or h.ino != ino:
            return _errno.EBADF
        if is_internal(ino):
            return self.internal.write(ctx, ino, fh, data)
        if h.writer is None:
            return _errno.EACCES
        if off + len(data) > MAX_FILE_SIZE:
            return _errno.EFBIG
        h.begin_write()
        try:
            # uploads triggered by this write are queued under the
            # requesting uid (per-tenant fair queueing, ISSUE 6)
            with tenant_scope(ctx.uid):
                # Kernel-writeback mode: the kernel positions O_APPEND
                # writes itself and flushes whole cached pages at explicit
                # offsets — re-deriving EOF here would double-place the
                # data.
                if h.flags & os.O_APPEND and not self.always_readable_handles:
                    with self._append_lock:
                        st, attr = self.getattr(ctx, ino)
                        if st != 0:
                            return st
                        return h.writer.write(attr.length, data)
                return h.writer.write(off, data)
        finally:
            h.end_write()

    def flush(self, ctx: Context, ino: int, fh: int, lock_owner: int = 0) -> int:
        h = self.handles.get(fh)
        if h is None:
            return _errno.EBADF
        if is_internal(ino):
            # virtual files: nothing to flush and no POSIX locks — the
            # unlock-on-close below would dial the meta engine, making
            # `.status`/`.stats` reads fail at CLOSE during the very
            # outage they exist to observe (ISSUE 14, found live)
            return 0
        if h.writer is not None:
            st = h.writer.flush()
            if st != 0:
                return st
        # fsync barrier for the checkpoint write plane (ISSUE 13): the
        # slice commits the writer just queued — and the create that
        # opened this file — must be durably committed before fsync
        # acks; a deferred failure surfaces here, never silently (the
        # vfs/writer.py sticky-error contract at the meta layer).
        # OUTSIDE the writer guard: POSIX fsync flushes the FILE, so an
        # O_RDONLY fd of a file with pending batched mutations must
        # drain them too.
        st = self.meta.sync_meta(ino)
        if st != 0:
            return st
        if h.writer is not None:
            self.cache.invalidate_attr(ino)  # committed length/mtime
        # Drop this owner's POSIX locks on close, per POSIX close(2).
        if lock_owner and hasattr(self.meta, "setlk"):
            try:
                self.meta.setlk(
                    ctx, ino, lock_owner, self.meta.F_UNLCK, 0,
                    0x7FFFFFFFFFFFFFFF
                )
            except OSError as e:
                # (POSIX results are RETURN codes here — setlk only
                # raises for engine faults: MetaNetworkError pre-trip,
                # MetaUnavailableError once the breaker is open)
                # best-effort during a meta outage (ISSUE 14): the engine
                # that holds the lock table is dark, so the lock is
                # unenforceable right now and dies with the session
                # either way — failing the CLOSE of (usually unlocked)
                # files would turn every degraded read into an EIO
                logger.warning("unlock-on-close skipped (meta down): %s", e)
        return 0

    def fsync(self, ctx: Context, ino: int, fh: int) -> int:
        return self.flush(ctx, ino, fh)

    def release(self, ctx: Context, ino: int, fh: int) -> int:
        h = self.handles.remove(fh)
        if h is None:
            return 0
        if is_internal(ino):
            self.internal.release(ino, fh)
            return 0
        h.wait_quiet()
        st = 0
        if h.writer is not None:
            st = self.writer.close(ino)
            self.cache.invalidate_attr(ino)
        # meta close is the last write-batch barrier for this inode: a
        # deferred commit that failed after the final fsync surfaces here
        st2 = self.meta.close(ctx, ino)
        return st or st2

    # -- data shaping ------------------------------------------------------

    def truncate_ino(self, ctx: Context, ino: int, length: int) -> tuple[int, Attr]:
        st = self.writer.flush(ino)
        if st != 0:
            return st, Attr()
        st, attr = self.meta.truncate(ctx, ino, length)
        if st == 0:
            self.cache.attr_mutated(ino, attr)
            self.writer.truncate(ino, length)
        return st, attr

    def fallocate(self, ctx: Context, ino: int, fh: int, mode: int, off: int, size: int) -> int:
        if self.conf.readonly:
            return _errno.EROFS
        h = self.handles.get(fh)
        if h is None or h.writer is None:
            return _errno.EBADF
        if off + size > MAX_FILE_SIZE:
            return _errno.EFBIG
        st = self.writer.flush(ino)
        if st != 0:
            return st
        st = self.meta.fallocate(ctx, ino, mode, off, size)
        if st == 0:
            self.cache.invalidate_attr(ino)
        return st

    def copy_file_range(
        self, ctx: Context, fin: int, off_in: int, fout: int, off_out: int,
        size: int, flags: int = 0,
    ) -> tuple[int, int]:
        if self.conf.readonly:
            return _errno.EROFS, 0
        for ino in (fin, fout):
            st = self.writer.flush(ino)
            if st != 0:
                return st, 0
        st, copied = self.meta.copy_file_range(ctx, fin, off_in, fout, off_out, size, flags)
        if st == 0:
            self.cache.invalidate_attr(fout)
        return st, copied

    # -- xattr / statfs / ACLs ---------------------------------------------
    # system.posix_acl_* xattrs bridge to GetFacl/SetFacl meta ops with the
    # kernel wire codec (reference pkg/vfs/vfs.go:1040-1160, 1348-1420).

    _ACL_XATTRS = {
        b"system.posix_acl_access": 1,   # acl.TYPE_ACCESS
        b"system.posix_acl_default": 2,  # acl.TYPE_DEFAULT
    }

    def _acl_enabled(self) -> bool:
        return bool(self.fmt is not None and self.fmt.enable_acl)

    def getxattr(self, ctx, ino, name) -> tuple[int, bytes]:
        acl_type = self._ACL_XATTRS.get(bytes(name))
        if acl_type is not None:
            from ..meta import acl as _acl

            if not self._acl_enabled():
                return _errno.ENOTSUP, b""
            st, rule = self.meta.get_facl(ctx, ino, acl_type)
            if st != 0:
                return st, b""
            return 0, _acl.to_xattr(rule)
        return self.meta.getxattr(ctx, ino, name)

    def setxattr(self, ctx, ino, name, value, flags=0) -> int:
        if self.conf.readonly:
            return _errno.EROFS
        acl_type = self._ACL_XATTRS.get(bytes(name))
        if acl_type is not None:
            from ..meta import acl as _acl

            if not self._acl_enabled():
                return _errno.ENOTSUP
            rule = _acl.from_xattr(bytes(value))
            if rule is None:
                return _errno.EINVAL
            st = self.meta.set_facl(ctx, ino, acl_type, rule)
        else:
            st = self.meta.setxattr(ctx, ino, name, value, flags)
        if st == 0:
            self.cache.invalidate_attr(ino)  # mode/ctime changed
        return st

    def listxattr(self, ctx, ino) -> tuple[int, list[bytes]]:
        st, names = self.meta.listxattr(ctx, ino)
        if st == 0 and self._acl_enabled():
            st2, attr = self.getattr(ctx, ino)
            if st2 == 0:
                if getattr(attr, "access_acl", 0):
                    names = list(names) + [b"system.posix_acl_access"]
                if getattr(attr, "default_acl", 0):
                    names = list(names) + [b"system.posix_acl_default"]
        return st, names

    def removexattr(self, ctx, ino, name) -> int:
        if self.conf.readonly:
            return _errno.EROFS
        acl_type = self._ACL_XATTRS.get(bytes(name))
        if acl_type is not None:
            from ..meta import acl as _acl

            if not self._acl_enabled():
                return _errno.ENOTSUP
            st = self.meta.set_facl(ctx, ino, acl_type, _acl.empty_rule())
        else:
            st = self.meta.removexattr(ctx, ino, name)
        if st == 0:
            self.cache.invalidate_attr(ino)
        return st

    def statfs(self, ctx) -> tuple[int, int, int, int]:
        return self.meta.statfs(ctx)

    # -- lifecycle / seamless upgrade --------------------------------------

    def dump_handles(self) -> list[dict]:
        """Serializable open-handle state for fd-passing takeover
        (reference vfs/handle.go:302 dump). Writers must be flushed by the
        caller first — only structural state crosses the boundary."""
        out = []
        for h in self.handles.all():
            if is_internal(h.ino):
                continue  # internal virtual files don't survive a swap
            out.append({
                "fh": h.fh,
                "ino": h.ino,
                "flags": h.flags,
                "lock_owner": h.lock_owner,
                "dir": h.reader is None and h.writer is None,
            })
        return out

    def restore_handles(self, dumped: list[dict]) -> None:
        """Rebuild the handle table from a predecessor's dump
        (reference vfs/handle.go:351 restore)."""
        from ..meta.context import BACKGROUND

        for d in dumped:
            h = self.handles.insert(int(d["fh"]), int(d["ino"]), int(d["flags"]))
            h.lock_owner = int(d.get("lock_owner", 0))
            if d.get("dir"):
                continue
            accmode = h.flags & os.O_ACCMODE
            if accmode in (os.O_RDONLY, os.O_RDWR) or self.always_readable_handles:
                h.reader = self.reader.open(h.ino)
            if accmode in (os.O_WRONLY, os.O_RDWR):
                st, attr = self.meta.getattr(BACKGROUND, h.ino)
                h.writer = self.writer.open(h.ino, attr.length if st == 0 else 0)
            # the meta open-file refcount moved with the session id; the
            # local openfile cache just needs the entry back
            self.meta.open(BACKGROUND, h.ino, 0)

    def flush_all(self) -> int:
        return self.writer.flush_all()

    def close(self) -> None:
        self.writer.close_all()
        self.store.flush_all()
        self.reader.close()
        self.kernel_notifier = None
        if hasattr(self.meta, "off_invalidate"):
            self.meta.off_invalidate(self._remote_invalidate)
