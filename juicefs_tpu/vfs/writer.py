"""DataWriter: buffered, slice-ordered write pipeline.

Mirrors the behavior of the reference's pkg/vfs/writer.go:

  - a file's writes split at 64 MiB chunk boundaries (fileWriter.Write
    writer.go:290) into per-chunk writers;
  - each contiguous run of bytes becomes one write-once *slice*
    (findWritableSlice writer.go:159: append to the open tail slice when the
    write continues it, else start a new slice);
  - block-complete data uploads asynchronously as it accumulates
    (chunk.WSlice.flush_to), and slices are committed to the metadata
    engine strictly in slice-creation order per chunk (commitThread
    writer.go:181-216) so a crash never exposes later writes without
    earlier ones;
  - flush()/fsync() is the barrier: finish every slice upload, then drain
    the ordered commits (fileWriter.flush writer.go:349);
  - a background flusher finishes slices idle for >5 s and chunks holding
    too many open slices (writer.go:181 auto-flush), bounding buffered
    memory and metadata staleness.

Threading model: one lock per file writer; the store's own upload pool does
the heavy lifting, so these locks are held only for buffer bookkeeping.
"""

from __future__ import annotations

import errno as _errno
import threading
import time
from typing import Optional

from ..chunk import CachedStore
from ..meta.base import BaseMeta
from ..meta.types import CHUNK_SIZE, Slice
from ..utils import get_logger, lockwatch

logger = get_logger("vfs.writer")

FLUSH_IDLE_SEC = 5.0
MAX_OPEN_SLICES_PER_CHUNK = 3


class SliceWriter:
    """One write-once slice being assembled (reference sliceWriter :68-125)."""

    __slots__ = ("id", "pos", "length", "ws", "done", "committed", "last_write")

    def __init__(self, sid: int, store: CachedStore, pos: int):
        self.id = sid
        self.pos = pos  # offset of this slice within its chunk
        self.length = 0
        self.ws = store.new_writer(sid)
        self.done = False
        self.committed = False
        self.last_write = time.monotonic()

    def writable_at(self, coff: int) -> bool:
        """Accept writes appending to, or rewriting within, the still-
        buffered tail (full blocks below are already uploaded)."""
        if self.done:
            return False
        uploaded = (self.length // self.ws.bs) * self.ws.bs
        return self.pos + uploaded <= coff <= self.pos + self.length

    def write(self, coff: int, data: bytes) -> None:
        off = coff - self.pos
        self.ws.write_at(data, off)
        self.length = max(self.length, off + len(data))
        # Upload any block this write just completed.
        self.ws.flush_to(self.length)
        self.last_write = time.monotonic()

    def finish(self) -> None:
        """Upload barrier (meta commit happens separately, in order)."""
        if not self.done:
            self.ws.finish(self.length)
            self.done = True


class ChunkWriter:
    """All open slices of one 64 MiB chunk (reference chunkWriter)."""

    def __init__(self, fw: "FileWriter", indx: int):
        self.fw = fw
        self.indx = indx
        self.slices: list[SliceWriter] = []

    def write(self, coff: int, data: bytes) -> int:
        sw = self._find_writable(coff)
        if sw is None:
            sw = SliceWriter(self.fw.dw.meta.new_slice(), self.fw.dw.store, coff)
            self.slices.append(sw)
        try:
            sw.write(coff, data)
        except IOError as e:
            logger.warning("write slice %d failed: %s", sw.id, e)
            return _errno.EIO
        return 0

    def _find_writable(self, coff: int) -> Optional[SliceWriter]:
        # Only the newest slice may accept writes: an older slice is
        # shadowed wherever they overlap, and appending to it could
        # resurrect stale bytes (reference findWritableSlice :159-179).
        if self.slices and self.slices[-1].writable_at(coff):
            return self.slices[-1]
        return None

    def commit_ready(self) -> int:
        """Commit the finished prefix of the slice list to meta, in order."""
        while self.slices and self.slices[0].done:
            sw = self.slices[0]
            slc = Slice(pos=sw.pos, id=sw.id, size=sw.length, off=0, len=sw.length)
            st = self.fw.dw.meta.write_chunk(self.fw.ino, self.indx, sw.pos, slc)
            if st != 0:
                logger.error("commit slice %d of ino %d: errno %d", sw.id, self.fw.ino, st)
                return st
            sw.committed = True
            self.slices.pop(0)
        return 0

    def flush(self) -> int:
        for sw in self.slices:
            try:
                sw.finish()
            except IOError as e:
                # Keep the slices: the error must stay visible to every
                # later flush/fsync (no silently-successful retry).
                logger.error("finish slice %d: %s", sw.id, e)
                return _errno.EIO
        return self.commit_ready()

    def overlaps(self, start: int, end: int) -> bool:
        return any(
            sw.pos < end and sw.pos + max(sw.length, 1) > start for sw in self.slices
        )

    def flush_idle(self, idle_before: float) -> int:
        """Finish slices idle past the deadline or beyond the open cap."""
        excess = len(self.slices) - MAX_OPEN_SLICES_PER_CHUNK
        for i, sw in enumerate(self.slices):
            if sw.done:
                continue
            if sw.last_write < idle_before or i < excess:
                try:
                    sw.finish()
                except IOError as e:
                    logger.error("finish slice %d: %s", sw.id, e)
                    self.fw.err = _errno.EIO
                    return _errno.EIO
        return self.commit_ready()


class FileWriter:
    """Write state of one open file (reference fileWriter writer.go:35)."""

    def __init__(self, dw: "DataWriter", ino: int, length: int):
        self.dw = dw
        self.ino = ino
        self.length = length
        self.lock = threading.RLock()
        self.chunks: dict[int, ChunkWriter] = {}
        self.refs = 1
        # Sticky error (reference fileWriter err): once a flush fails, every
        # later write/flush reports it until the file is closed, so an
        # application retrying fsync cannot see a false success.
        self.err = 0

    def write(self, off: int, data: bytes) -> int:
        with self.lock:
            if self.err:
                return self.err
            pos = off
            mv = memoryview(data)
            while mv:
                indx, coff = divmod(pos, CHUNK_SIZE)
                n = min(len(mv), CHUNK_SIZE - coff)
                cw = self.chunks.get(indx)
                if cw is None:
                    cw = self.chunks[indx] = ChunkWriter(self, indx)
                # pass the view through: WSlice.write_at copies into its
                # block buffer, so a bytes() here would copy every byte
                # twice
                st = cw.write(coff, mv[:n])
                if st != 0:
                    return st
                mv = mv[n:]
                pos += n
            self.length = max(self.length, pos)
            return 0

    def flush(self) -> int:
        # Intentional hold-while-blocking: flush IS the per-file commit
        # barrier — it waits out slice uploads under the file's own lock
        # so concurrent writers/readers of THIS file serialize against
        # the barrier.  Deadlock-free because upload-pool workers never
        # take FileWriter locks (docs/ARCHITECTURE.md "Checked
        # concurrency contracts").
        with self.lock, lockwatch.permit(
                "per-file flush barrier: upload workers never take "
                "FileWriter.lock, so waiting them out under it cannot "
                "cycle"):
            if self.err:
                return self.err
            for indx in sorted(self.chunks):
                st = self.chunks[indx].flush()
                if st != 0:
                    self.err = st
                    return st
            self.chunks = {i: c for i, c in self.chunks.items() if c.slices}
            return 0

    def flush_if_overlaps(self, off: int, size: int) -> int:
        """Flush only when buffered writes overlap [off, off+size); avoids
        finalizing the open tail slice on every interleaved read."""
        with self.lock:
            if self.err:
                return self.err
            start_indx, end_indx = off // CHUNK_SIZE, (off + size - 1) // CHUNK_SIZE
            for indx in range(start_indx, end_indx + 1):
                cw = self.chunks.get(indx)
                if cw is None:
                    continue
                c0 = max(off - indx * CHUNK_SIZE, 0)
                c1 = min(off + size - indx * CHUNK_SIZE, CHUNK_SIZE)
                if cw.overlaps(c0, c1):
                    return self.flush()
            return 0

    def has_pending(self) -> bool:
        with self.lock:
            return any(c.slices for c in self.chunks.values())

    def _background_flush(self) -> None:
        with self.lock, lockwatch.permit(
                "idle-slice flush: same per-file barrier contract as "
                "FileWriter.flush"):
            deadline = time.monotonic() - FLUSH_IDLE_SEC
            for cw in list(self.chunks.values()):
                cw.flush_idle(deadline)
            self.chunks = {i: c for i, c in self.chunks.items() if c.slices}


class DataWriter:
    """Per-mount writer registry + background flusher (writer.go:512-559)."""

    def __init__(self, meta: BaseMeta, store: CachedStore, flush_interval: float = 1.0):
        self.meta = meta
        self.store = store
        self._files: dict[int, FileWriter] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(flush_interval,), daemon=True,
            name="vfs-writer-flush",
        )
        self._flusher.start()

    def buffered_bytes(self) -> int:
        """Bytes currently held in un-uploaded write buffers — the memory
        accounting the reference keeps in pkg/utils/alloc.go + the
        used_buffer_size_bytes gauge (vfs.go:1290)."""
        total = 0
        with self._lock:
            writers = list(self._files.values())
        for fw in writers:
            with fw.lock:
                for cw in fw.chunks.values():
                    for sw in cw.slices:
                        for buf in sw.ws._blocks.values():
                            total += len(buf)
        return total

    def open(self, ino: int, length: int) -> FileWriter:
        with self._lock:
            fw = self._files.get(ino)
            if fw is None:
                fw = self._files[ino] = FileWriter(self, ino, length)
            else:
                fw.refs += 1
                fw.length = max(fw.length, length)
            return fw

    def close(self, ino: int) -> int:
        with self._lock:
            fw = self._files.get(ino)
            if fw is None:
                return 0
            fw.refs -= 1
            if fw.refs > 0:
                return 0
        # Flush while the writer is still registered: a concurrent open()
        # must find (and reuse) it, not create a second writer whose newer
        # slices could be shadowed by our late commits.
        st = fw.flush()
        with self._lock:
            if fw.refs == 0 and self._files.get(ino) is fw:
                self._files.pop(ino, None)
        return st

    def find(self, ino: int) -> Optional[FileWriter]:
        with self._lock:
            return self._files.get(ino)

    def flush(self, ino: int) -> int:
        fw = self.find(ino)
        return fw.flush() if fw is not None else 0

    def flush_all(self) -> int:
        with self._lock:
            files = list(self._files.values())
        st = 0
        for fw in files:
            st = fw.flush() or st
        # flush_all is the unmount/takeover barrier: the slice commits
        # queued above must also clear the meta write batch (ISSUE 13)
        st2 = self.meta.sync_meta()
        return st or st2

    def get_length(self, ino: int) -> Optional[int]:
        """Buffered (not yet committed) length, for read-your-writes."""
        fw = self.find(ino)
        return fw.length if fw is not None else None

    def truncate(self, ino: int, length: int) -> None:
        fw = self.find(ino)
        if fw is not None:
            with fw.lock:
                fw.length = length

    def close_all(self) -> None:
        self._stop.set()  # wake the flusher out of its interval sleep
        self.flush_all()
        self._flusher.join(timeout=10.0)

    def _flush_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            with self._lock:
                files = list(self._files.values())
            for fw in files:
                try:
                    fw._background_flush()
                except Exception:
                    logger.exception("background flush of ino %d", fw.ino)
