"""Access log: per-op trace stream (reference pkg/vfs/accesslog.go:64-140).

Every VFS operation `logit`s a line, but lines are only materialized while
at least one reader holds the virtual `.accesslog` file open — otherwise
logging is a near-free atomic check, exactly like the reference. Each
reader gets its own bounded ring buffer so a slow consumer cannot block
the filesystem or other readers.
"""

from __future__ import annotations

import threading
import time
from collections import deque

MAX_BUFFERED_LINES = 10240


class AccessLogger:
    def __init__(self):
        self._lock = threading.Lock()
        self._readers: dict[int, deque[bytes]] = {}
        self._active = False

    def open_reader(self, fh: int) -> None:
        with self._lock:
            self._readers[fh] = deque(maxlen=MAX_BUFFERED_LINES)
            self._active = True

    def close_reader(self, fh: int) -> None:
        with self._lock:
            self._readers.pop(fh, None)
            self._active = bool(self._readers)

    @property
    def active(self) -> bool:
        return self._active

    def logit(self, op: str, args: str, err: int, dur: float, pid: int = 0,
              uid: int = 0, gid: int = 0) -> None:
        if not self._active:
            return
        ts = time.time()
        # real caller identity (reference accesslog.go logs the request's
        # uid/gid/pid, not the server's); line format otherwise unchanged
        line = (
            f"{time.strftime('%Y.%m.%d %H:%M:%S', time.localtime(ts))}"
            f".{int(ts % 1 * 1e6):06d} [uid:{uid},gid:{gid},pid:{pid}] "
            f"{op} ({args}): {'OK' if err == 0 else f'errno {err}'} "
            f"<{dur:.6f}>\n"
        ).encode()
        with self._lock:
            for buf in self._readers.values():
                buf.append(line)

    def read(self, fh: int, max_bytes: int = 1 << 16) -> bytes:
        """Drain buffered lines for one reader (blocking up to 1s like the
        reference's readers so `tail -f` style consumers don't spin)."""
        deadline = time.time() + 1.0
        while True:
            with self._lock:
                buf = self._readers.get(fh)
                if buf is None:
                    return b""
                out = bytearray()
                while buf:
                    line = buf[0]
                    if len(out) + len(line) > max_bytes:
                        # Never exceed the requested size: an oversized FUSE
                        # reply is rejected by the kernel (EIO). Split a
                        # line only when nothing fits otherwise.
                        if not out:
                            out += line[:max_bytes]
                            buf[0] = line[max_bytes:]
                        break
                    out += buf.popleft()
            if out or time.time() >= deadline:
                return bytes(out)
            time.sleep(0.02)
