"""VFS core (reference: pkg/vfs, SURVEY.md §2.1).

The filesystem layer every presentation adapter (FUSE, S3 gateway, WebDAV,
SDK) serves: handle table, buffered slice-ordered DataWriter, readahead
DataReader, and the VFS facade tying them to the meta engine + chunk store.
"""

from .handles import Handle, HandleTable
from .reader import DataReader, FileReader
from .vfs import ROOT_INO, VFS, VFSConfig
from .writer import DataWriter, FileWriter

__all__ = [
    "VFS",
    "VFSConfig",
    "ROOT_INO",
    "Handle",
    "HandleTable",
    "DataReader",
    "FileReader",
    "DataWriter",
    "FileWriter",
]
