"""`status` / `info` / `summary` / `rmr`: volume and path inspection tools
(reference cmd/status.go, cmd/info.go, cmd/summary.go, cmd/rmr.go)."""

from __future__ import annotations

import json

from ..meta.context import BACKGROUND
from ..meta.types import CHUNK_SIZE, TYPE_DIRECTORY


def add_parser(sub):
    s = sub.add_parser("status", help="show volume status")
    s.add_argument("meta_url")
    s.set_defaults(func=run_status)

    i = sub.add_parser("info", help="show file/dir internals")
    i.add_argument("meta_url")
    i.add_argument("path")
    i.set_defaults(func=run_info)

    m = sub.add_parser("summary", help="du-like tree summary")
    m.add_argument("meta_url")
    m.add_argument("path")
    m.set_defaults(func=run_summary)

    r = sub.add_parser("rmr", help="remove a tree recursively (server-side)")
    r.add_argument("meta_url")
    r.add_argument("path")
    r.add_argument("--skip-trash", action="store_true")
    r.set_defaults(func=run_rmr)


def run_status(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    sessions = m.do_list_sessions()
    total, avail, iused, iavail = m.statfs(BACKGROUND)
    print(json.dumps({
        "format": json.loads(fmt.remove_secret().to_json()),
        "sessions": [json.loads(s.to_json()) for s in sessions],
        "used_space": total - avail,
        "inodes_used": iused,
        "object_plane": _object_plane_status(fmt),
    }, indent=2, default=str))
    return 0


def _object_plane_status(fmt) -> dict:
    """Probe the volume's storage stack once from THIS process and report
    the resilience configuration.  Deliberately NOT a breaker snapshot: a
    freshly built stack always starts CLOSED/empty, and presenting that
    as health would contradict a mount mid-outage.  Live breaker/ladder
    state belongs to the mount's `.status` internal file."""
    try:
        from ..object.interface import NotFoundError
        from ..object.resilient import resilient
        from . import storage_for

        store = resilient(storage_for(fmt))
        try:
            try:
                store._s.head(".jfs-status-probe")  # direct: one attempt
                probe = "ok"
            except NotFoundError:
                probe = "ok"
            except Exception as e:
                probe = f"unreachable: {e}"
            h = store.health()
            return {
                "backend": h["backend"],
                "probe": probe,
                "policy": h["policy"],
                "hedge": h["hedge"],
                "live_state": "read <mountpoint>/.status on an active "
                              "mount for breaker/ladder state",
            }
        finally:
            store.close()
    except Exception as e:  # status must never fail on a broken stack
        return {"error": str(e)}


def run_info(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    st, ino, attr = m.resolve(BACKGROUND, args.path)
    if st:
        print(f"resolve {args.path}: errno {st}")
        return 1
    out = {
        "path": args.path,
        "inode": ino,
        "type": attr.typ,
        "mode": oct(attr.mode),
        "uid": attr.uid,
        "gid": attr.gid,
        "length": attr.length,
        "nlink": attr.nlink,
    }
    if attr.typ != TYPE_DIRECTORY:
        chunks = []
        for indx in range((attr.length + CHUNK_SIZE - 1) // CHUNK_SIZE):
            st, slices = m.read_chunk(ino, indx)
            if st == 0 and slices:
                chunks.append({
                    "index": indx,
                    "slices": [
                        {"pos": s.pos, "id": s.id, "size": s.size,
                         "off": s.off, "len": s.len}
                        for s in slices
                    ],
                })
        out["chunks"] = chunks
    print(json.dumps(out, indent=2))
    return 0


def run_summary(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    st, ino, attr = m.resolve(BACKGROUND, args.path)
    if st:
        print(f"resolve {args.path}: errno {st}")
        return 1
    st, s = m.summary(BACKGROUND, ino)
    if st:
        return 1
    print(json.dumps({
        "path": args.path, "files": s.files, "dirs": s.dirs,
        "length": s.length, "size": s.size,
    }, indent=2))
    return 0


def run_rmr(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    parent_path, _, name = args.path.rstrip("/").rpartition("/")
    st, parent, _ = m.resolve(BACKGROUND, parent_path or "/")
    if st:
        print(f"resolve {parent_path}: errno {st}")
        return 1
    st, removed = m.remove_recursive(
        BACKGROUND, parent, name.encode(), skip_trash=args.skip_trash
    )
    print(f"removed {removed} entries (errno {st})")
    return 0 if st == 0 else 1
