"""`stats` / `profile` / `debug` / `clone` / `restore` / `destroy`
(reference cmd/stats.go, cmd/profile.go, cmd/debug.go, cmd/clone.go,
cmd/restore.go, cmd/destroy.go).

stats/profile consume the mount's virtual files (.stats Prometheus dump,
.accesslog trace) exactly like the reference; clone goes through the
.control protocol when given a mount path, or straight to meta.
"""

from __future__ import annotations

import json
import os
import re
import time
from collections import defaultdict

from ..meta.context import BACKGROUND
from ..meta.types import TRASH_INODE
from ..utils import get_logger

logger = get_logger("cmd.stats")


def add_parser(sub):
    s = sub.add_parser("stats", help="show metrics of a mounted volume")
    s.add_argument("mountpoint")
    s.add_argument("--filter", default="",
                   help="regular expression matched against metric lines "
                        "(reference --filter semantics); lines without a "
                        "match are hidden")
    s.set_defaults(func=run_stats)

    p = sub.add_parser("profile", help="aggregate live op latencies from a mount")
    p.add_argument("mountpoint")
    p.add_argument("--duration", type=float, default=2.0, help="seconds to sample")
    p.add_argument("--trace", default="", metavar="DIR",
                   help="sample span events from the mount's .trace stream "
                        "instead of .accesslog and write a chrome://tracing-"
                        "loadable trace_event JSON into DIR")
    p.set_defaults(func=run_profile)

    d = sub.add_parser("debug", help="collect diagnostics from a mount")
    d.add_argument("mountpoint")
    d.add_argument("--out", default="", help="output directory (default: stdout)")
    d.set_defaults(func=run_debug)

    c = sub.add_parser("clone", help="server-side O(meta) copy")
    c.add_argument("meta_url")
    c.add_argument("src", help="volume-absolute source path")
    c.add_argument("dst", help="volume-absolute destination path")
    c.set_defaults(func=run_clone)

    r = sub.add_parser("restore", help="restore entries from trash")
    r.add_argument("meta_url")
    r.add_argument("hour", nargs="?", default="",
                   help="trash hour dir (YYYY-MM-DD-HH); default: list trash")
    r.set_defaults(func=run_restore)

    x = sub.add_parser("destroy", help="destroy a volume: all data + metadata")
    x.add_argument("meta_url")
    x.add_argument("--yes", action="store_true", help="required confirmation")
    x.set_defaults(func=run_destroy)


def run_stats(args) -> int:
    pat = None
    if args.filter:
        try:
            pat = re.compile(args.filter)
        except re.error as e:
            print(f"stats: invalid --filter regex {args.filter!r}: {e}")
            return 1
    with open(os.path.join(args.mountpoint, ".stats"), "rb") as f:
        text = f.read().decode()
    for line in text.splitlines():
        if pat is not None and not pat.search(line):
            continue
        if line and not line.startswith("#"):
            print(line)
    return 0


_LOG_RE = re.compile(r"\[uid:\d+,gid:\d+,pid:\d+\] (\w+) \(.*\): (\S+).* <([0-9.]+)>")


def open_stream(path: str) -> int:
    """Open a live virtual stream (.accesslog / .trace) uncached.

    O_DIRECT first: kernels that ignore the server's FOPEN_DIRECT_IO
    (gVisor-style FUSE) would otherwise serve a stream through the page
    cache, replaying stale pages instead of fresh lines. FUSE imposes no
    O_DIRECT alignment constraints; fall back to a plain open where
    O_DIRECT is unsupported."""
    try:
        return os.open(path, os.O_RDONLY | getattr(os, "O_DIRECT", 0))
    except OSError:
        return os.open(path, os.O_RDONLY)


# event keys that are structure, not user attrs, when converting to the
# Chrome trace_event format
_SPAN_FIELDS = ("ts", "dur", "trace", "id", "parent", "layer", "op", "stage")


def _chrome_event(ev: dict) -> dict:
    """One .trace span event -> one Chrome trace_event 'X' entry
    (loadable in chrome://tracing and Perfetto)."""
    name = str(ev.get("op", "?"))
    if ev.get("stage"):
        name += ":" + str(ev["stage"])
    args = {k: v for k, v in ev.items() if k not in _SPAN_FIELDS}
    args["span_id"] = ev.get("id", 0)
    args["parent_id"] = ev.get("parent", 0)
    return {
        "name": name,
        "cat": str(ev.get("layer", "?")),
        "ph": "X",
        "ts": float(ev.get("ts", 0.0)) * 1e6,
        "dur": max(float(ev.get("dur", 0.0)) * 1e6, 0.1),
        "pid": 1,
        "tid": int(ev.get("trace", 0)),
        "args": args,
    }


def run_trace_profile(args) -> int:
    """`profile --trace DIR`: sample the mount's .trace span stream for
    --duration seconds and write a chrome://tracing JSON into DIR."""
    events: list[dict] = []
    deadline = time.time() + args.duration
    buf = b""
    fd = open_stream(os.path.join(args.mountpoint, ".trace"))
    try:
        while time.time() < deadline:
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                # EOF (size-clamping kernel exhausted STREAM_LENGTH, or
                # unmounted): don't spin hot on instant empty reads
                time.sleep(0.05)
                continue
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if isinstance(ev, dict):
                    events.append(ev)
    finally:
        os.close(fd)
    os.makedirs(args.trace, exist_ok=True)
    path = os.path.join(args.trace, "juicefs-trace.json")
    with open(path, "w") as out:
        json.dump(
            {
                "traceEvents": [_chrome_event(ev) for ev in events],
                "displayTimeUnit": "ms",
            },
            out,
        )
    per_layer: dict[str, int] = defaultdict(int)
    for ev in events:
        per_layer[str(ev.get("layer", "?"))] += 1
    summary = ", ".join(f"{k}:{v}" for k, v in sorted(per_layer.items()))
    print(f"sampled {len(events)} spans ({summary or 'none'}) -> {path}")
    return 0


def run_profile(args) -> int:
    if getattr(args, "trace", ""):
        return run_trace_profile(args)
    stats: dict[str, list[float]] = defaultdict(list)
    deadline = time.time() + args.duration
    fd = open_stream(os.path.join(args.mountpoint, ".accesslog"))
    try:
        while time.time() < deadline:
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                time.sleep(0.05)  # EOF: see run_trace_profile
                continue
            for line in chunk.decode(errors="replace").splitlines():
                m = _LOG_RE.search(line)
                if m:
                    stats[m.group(1)].append(float(m.group(3)))
    finally:
        os.close(fd)
    print(f"{'op':<16}{'count':>8}{'avg_ms':>10}{'total_ms':>10}")
    for op, durs in sorted(stats.items(), key=lambda kv: -sum(kv[1])):
        total = sum(durs)
        print(f"{op:<16}{len(durs):>8}{total / len(durs) * 1e3:>10.3f}"
              f"{total * 1e3:>10.1f}")
    return 0


def run_debug(args) -> int:
    out = {}
    for name in (".config", ".stats"):
        try:
            with open(os.path.join(args.mountpoint, name), "rb") as f:
                out[name] = f.read().decode()
        except OSError as e:
            out[name] = f"<unreadable: {e}>"
    try:
        sv = os.statvfs(args.mountpoint)
        out["statvfs"] = {
            "blocks": sv.f_blocks, "bavail": sv.f_bavail, "files": sv.f_files,
        }
    except OSError:
        pass
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        for name, content in out.items():
            with open(os.path.join(args.out, name.lstrip(".") + ".txt"), "w") as f:
                f.write(content if isinstance(content, str) else json.dumps(content))
        print(f"diagnostics written to {args.out}")
    else:
        print(json.dumps(out, indent=2)[:4000])
    return 0


def run_clone(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    if not hasattr(m, "clone"):
        print("meta engine does not support clone")
        return 1
    st, src_ino, _ = m.resolve(BACKGROUND, args.src)
    if st:
        print(f"resolve {args.src}: errno {st}")
        return 1
    parent_path, _, name = args.dst.rstrip("/").rpartition("/")
    st, parent, _ = m.resolve(BACKGROUND, parent_path or "/")
    if st:
        print(f"resolve {parent_path}: errno {st}")
        return 1
    st, new_ino = m.clone(BACKGROUND, src_ino, parent, name.encode())
    if st:
        print(f"clone failed: errno {st}")
        return 1
    print(f"cloned {args.src} -> {args.dst} (inode {new_ino})")
    return 0


def run_restore(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    st, hours = m.readdir(BACKGROUND, TRASH_INODE)
    if st:
        print("no trash")
        return 0
    hours = [e for e in hours if e.name not in (b".", b"..")]
    if not args.hour:
        for e in hours:
            st, entries = m.readdir(BACKGROUND, e.inode)
            n = len([x for x in entries if x.name not in (b".", b"..")])
            print(f"{e.name.decode()}: {n} entries")
        return 0
    hour_ino = next((e.inode for e in hours if e.name.decode() == args.hour), 0)
    if not hour_ino:
        print(f"no trash dir {args.hour}")
        return 1
    st, entries = m.readdir(BACKGROUND, hour_ino)
    restored = skipped = 0
    for e in entries:
        if e.name in (b".", b".."):
            continue
        try:
            parent_s, _, orig = e.name.split(b"-", 2)
            parent = int(parent_s)
        except ValueError:
            skipped += 1
            continue
        st, _, _ = m.rename(BACKGROUND, hour_ino, e.name, parent, orig)
        if st:
            logger.warning("restore %s: errno %d", e.name.decode(), st)
            skipped += 1
        else:
            restored += 1
    print(f"restored {restored}, skipped {skipped}")
    return 0


def run_destroy(args) -> int:
    from . import build_store, open_meta

    if not args.yes:
        print("refusing to destroy without --yes")
        return 1
    m, fmt = open_meta(args.meta_url)
    store = build_store(fmt)
    n = 0
    for obj in list(store.storage.list_all("")):
        try:
            store.storage.delete(obj.key)
            n += 1
        except Exception as e:
            logger.warning("delete %s: %s", obj.key, e)
    m.reset()
    print(f"destroyed volume {fmt.name}: {n} objects removed, metadata wiped")
    return 0
