"""`dump` / `load`: full metadata backup & restore (reference
pkg/meta/dump.go, cmd/dump.go, cmd/load.go).

Dump walks the raw ordered-KV space and emits every record (base64) plus
the Format — a complete, engine-portable snapshot analogous to the
reference's `dump --fast` binary backup; load replays it into any KV
engine (mem, sqlite3), enabling engine migration like the reference's
dump/load pair.
"""

from __future__ import annotations

import json
import sys

from ..meta import new_client
from ..utils import get_logger

logger = get_logger("cmd.dump")

FORMAT_KEY = b"setting"


def add_parser(sub):
    p = sub.add_parser("dump", help="dump metadata to JSON")
    p.add_argument("meta_url")
    p.add_argument("output", nargs="?", default="-", help="file or - for stdout")
    p.set_defaults(func=run_dump)

    l = sub.add_parser("load", help="load metadata from a dump")
    l.add_argument("meta_url")
    l.add_argument("input", nargs="?", default="-")
    l.add_argument("--force", action="store_true", help="overwrite non-empty engine")
    l.set_defaults(func=run_load)


def run_dump(args) -> int:
    from ..meta.dump import dump_doc

    m = new_client(args.meta_url)
    m.load()
    doc = dump_doc(m)
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        json.dump(doc, out)
        out.write("\n")
    finally:
        if out is not sys.stdout:
            out.close()
    logger.info("dumped %d records", len(doc["records"]))
    return 0


def run_load(args) -> int:
    from ..meta.dump import load_doc

    src = sys.stdin if args.input == "-" else open(args.input)
    try:
        doc = json.load(src)
    finally:
        if src is not sys.stdin:
            src.close()
    m = new_client(args.meta_url)
    n = load_doc(m, doc, force=args.force)
    print(f"loaded {n} records into {args.meta_url}")
    return 0
