"""`dump` / `load`: full metadata backup & restore (reference
pkg/meta/dump.go, cmd/dump.go, cmd/load.go).

Dump walks the raw ordered-KV space and emits every record (base64) plus
the Format — a complete, engine-portable snapshot analogous to the
reference's `dump --fast` binary backup; load replays it into any KV
engine (mem, sqlite3), enabling engine migration like the reference's
dump/load pair.
"""

from __future__ import annotations

import base64
import json
import sys

from ..meta import new_client
from ..meta.tkv_client import next_key
from ..utils import get_logger

logger = get_logger("cmd.dump")

FORMAT_KEY = b"setting"


def add_parser(sub):
    p = sub.add_parser("dump", help="dump metadata to JSON")
    p.add_argument("meta_url")
    p.add_argument("output", nargs="?", default="-", help="file or - for stdout")
    p.set_defaults(func=run_dump)

    l = sub.add_parser("load", help="load metadata from a dump")
    l.add_argument("meta_url")
    l.add_argument("input", nargs="?", default="-")
    l.add_argument("--force", action="store_true", help="overwrite non-empty engine")
    l.set_defaults(func=run_load)


def run_dump(args) -> int:
    m = new_client(args.meta_url)
    m.load()
    records = []
    for k, v in m.client.scan(b"", b"\xff" * 9):
        records.append(
            [base64.b64encode(k).decode(), base64.b64encode(v).decode()]
        )
    doc = {
        "version": 1,
        "engine": m.name(),
        "counters": {},
        "records": records,
    }
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        json.dump(doc, out)
        out.write("\n")
    finally:
        if out is not sys.stdout:
            out.close()
    logger.info("dumped %d records", len(records))
    return 0


def run_load(args) -> int:
    src = sys.stdin if args.input == "-" else open(args.input)
    try:
        doc = json.load(src)
    finally:
        if src is not sys.stdin:
            src.close()
    if doc.get("version") != 1:
        raise ValueError(f"unsupported dump version {doc.get('version')}")

    m = new_client(args.meta_url)
    existing = next(iter(m.client.scan(b"", b"\xff" * 9)), None)
    if existing is not None:
        if not args.force:
            raise RuntimeError("target meta engine not empty (use --force)")
        m.client.reset()

    records = [
        (base64.b64decode(k), base64.b64decode(v)) for k, v in doc["records"]
    ]

    def fn(tx):
        for k, v in records:
            tx.set(k, v)
        return 0

    m.client.txn(fn)
    print(f"loaded {len(records)} records into {args.meta_url}")
    return 0
