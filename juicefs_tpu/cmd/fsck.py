"""`fsck`: verify data integrity (reference cmd/fsck.go:75-230).

Lists `chunks/` objects, walks every slice from meta, and checks each
expected block exists with the right size. --verify-data additionally GETs
and decompresses every block; with the TPU hash backend it also streams
blocks through the JTH-256 pipeline and writes a content index, turning
fsck into the full-volume hash-verify workload from BASELINE.md.
"""

from __future__ import annotations

import json

from ..chunk.cached_store import block_key
from ..utils import get_logger

logger = get_logger("cmd.fsck")


def add_parser(sub):
    p = sub.add_parser("fsck", help="check volume integrity")
    p.add_argument("meta_url")
    p.add_argument("--verify-data", action="store_true",
                   help="GET + decompress every block")
    p.add_argument("--hash-index", default="",
                   help="also hash every block; write content index JSON here")
    p.add_argument("--hash-backend", default=None, help="cpu|xla|pallas")
    p.set_defaults(func=run)


def run(args) -> int:
    from . import build_store, open_meta

    m, fmt = open_meta(args.meta_url)
    # meta-attached store: reads of PUT-elided blocks resolve through the
    # content-ref plane (ISSUE 5) — without it every alias is "unreadable".
    # No indexer: fsck never uploads, and hashes through its own pipeline.
    store = build_store(fmt, args, meta=m, with_indexer=False)
    bs = fmt.block_size * 1024

    stored = {o.key: o.size for o in store.storage.list_all("chunks/")}
    slices = m.list_slices()

    # inline dedup (ISSUE 5): an elided block's bytes live under its
    # canonical — existence checks must translate through the alias plane
    try:
        from ..chunk.ingest import alias_map

        aliases = alias_map(m)
    except Exception:
        aliases = {}

    broken: list[str] = []
    checked = blocks = 0
    expected: dict[str, int] = {}
    for ino, slcs in slices.items():
        file_broken = False
        for s in slcs:
            if s.id == 0 or s.size == 0:
                continue
            for i in range((s.size + bs - 1) // bs):
                bsize = min(bs, s.size - i * bs)
                key = block_key(s.id, i, bsize)
                expected[key] = bsize
                blocks += 1
                if key not in stored and aliases.get(key, key) not in stored:
                    logger.error("ino %d: missing block %s", ino, key)
                    file_broken = True
                elif key not in stored:
                    pass  # deduped: bytes verified under the canonical key
                elif not fmt.compression and store.compressor.name == "" and stored[key] != bsize:
                    logger.error(
                        "ino %d: block %s size %d != %d", ino, key, stored[key], bsize
                    )
                    file_broken = True
        checked += 1
        if file_broken:
            broken.append(str(ino))

    if args.verify_data or args.hash_index:
        from ..chunk.indexer import pipeline_backend
        from ..tpu.jth256 import digest_hex
        from ..tpu.pipeline import HashPipeline, PipelineConfig

        backend = args.hash_backend or pipeline_backend(fmt.hash_backend)
        pipe = HashPipeline(
            PipelineConfig(backend=backend, pad_lanes=max(1, bs // 65536))
        )
        # Digests recorded by the write path (meta content index): a block
        # whose recomputed digest disagrees is silent corruption the
        # reference's existence/size fsck cannot see.
        recorded = {
            block_key(sid, indx, bsize): digest
            for sid, indx, bsize, digest in m.scan_block_digests()
        }

        def readable():
            for key, bsize in expected.items():
                if key not in stored and key not in aliases:
                    continue  # reported missing above; nothing to read
                try:
                    yield key, store._load_block(key, bsize, cache_after=False)
                except Exception as e:
                    logger.error("block %s unreadable: %s", key, e)
                    broken.append(key)

        bitrot = 0
        index = {}
        for k, d in pipe.hash_stream(readable()):
            index[k] = digest_hex(d)
            want = recorded.get(k)
            if want is not None and want != d:
                logger.error("block %s content digest mismatch (bitrot?)", k)
                broken.append(k)
                bitrot += 1
        if args.hash_index:
            with open(args.hash_index, "w") as f:
                json.dump(index, f, indent=1)
        print(
            f"verified {len(index)} blocks ({backend}); "
            f"{len(recorded)} indexed, {bitrot} digest mismatches"
        )

    print(f"checked {checked} files / {blocks} blocks; {len(broken)} broken")
    return 1 if broken else 0
