"""`quota` / `mdtest` (reference cmd/quota.go, cmd/mdtest.go).

quota: set/get/delete/list directory quotas (space/inode limits with
usage tracked transactionally up the ancestor chain).
mdtest: built-in metadata benchmark — tree create/stat/readdir/unlink
rates straight against the meta engine (reference mdtest.go:100,145).
"""

from __future__ import annotations

import json
import time

from ..meta.context import BACKGROUND
from ..utils import get_logger

logger = get_logger("cmd.quota")


def add_parser(sub):
    q = sub.add_parser("quota", help="manage directory quotas")
    q.add_argument("action", choices=["set", "get", "del", "list", "check"])
    q.add_argument("meta_url")
    q.add_argument("path", nargs="?", default="")
    q.add_argument("--space", type=float, default=0, help="space limit GiB (0=unlimited)")
    q.add_argument("--inodes", type=int, default=0, help="inode limit (0=unlimited)")
    q.add_argument("--repair", action="store_true",
                   help="with 'check': write recomputed usage back")
    q.set_defaults(func=run_quota)

    m = sub.add_parser("mdtest", help="metadata micro-benchmark")
    m.add_argument("meta_url")
    m.add_argument("--dirs", type=int, default=10)
    m.add_argument("--files", type=int, default=100, help="files per dir")
    m.add_argument("--via-vfs", action="store_true",
                   help="also measure stat rate through the VFS attr cache")
    m.set_defaults(func=run_mdtest)


def run_quota(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    if args.action == "list":
        quotas = m.list_dir_quotas()
        for ino, (sl, il, us, ui) in sorted(quotas.items()):
            paths = m.get_paths(ino)
            print(json.dumps({
                "inode": ino, "path": paths[0] if paths else "?",
                "space_limit": sl, "inode_limit": il,
                "used_space": us, "used_inodes": ui,
            }))
        return 0

    st, ino, attr = m.resolve(BACKGROUND, args.path or "/")
    if st:
        print(f"resolve {args.path}: errno {st}")
        return 1
    if args.action == "set":
        st = m.set_dir_quota(
            BACKGROUND, ino, int(args.space * (1 << 30)), args.inodes
        )
        if st:
            print(f"set quota: errno {st}")
            return 1
        print(f"quota set on {args.path}")
    elif args.action == "check":
        # recompute true usage; --repair heals hint-window drift. EAGAIN =
        # usage changed during the walk; retry a few times.
        import errno as _errno

        for _ in range(5):
            st, stored, actual = m.check_dir_quota(BACKGROUND, ino, args.repair)
            if st != _errno.EAGAIN:
                break
        if st:
            print(f"check quota: errno {st}")
            return 1
        drifted = stored != actual
        print(json.dumps({
            "path": args.path,
            "stored_space": stored[0], "stored_inodes": stored[1],
            "actual_space": actual[0], "actual_inodes": actual[1],
            "drifted": drifted, "repaired": bool(args.repair and drifted),
        }))
        return 1 if (drifted and not args.repair) else 0
    elif args.action == "get":
        rec = m.get_dir_quota(ino)
        if rec is None:
            print(f"no quota on {args.path}")
            return 1
        sl, il, us, ui = rec
        print(json.dumps({
            "path": args.path, "space_limit": sl, "inode_limit": il,
            "used_space": us, "used_inodes": ui,
            "space_pct": round(us / sl * 100, 1) if sl else 0,
        }))
    elif args.action == "del":
        m.del_dir_quota(ino)
        print(f"quota removed from {args.path}")
    return 0


def run_mdtest(args) -> int:
    from ..meta.types import ROOT_INODE
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    m.new_session()
    base_name = f"__mdtest_{int(time.time())}".encode()
    st, base, _ = m.mkdir(BACKGROUND, ROOT_INODE, base_name, 0o755)
    if st:
        print(f"mkdir: errno {st}")
        return 1
    results = {}

    t0 = time.perf_counter()
    dirs = []
    for d in range(args.dirs):
        st, dino, _ = m.mkdir(BACKGROUND, base, f"d{d}".encode(), 0o755)
        dirs.append(dino)
    results["dir_create_per_s"] = round(args.dirs / (time.perf_counter() - t0), 1)

    t0 = time.perf_counter()
    inos = []
    for dino in dirs:
        for f in range(args.files):
            st, ino, _ = m.create(BACKGROUND, dino, f"f{f}".encode(), 0o644)
            inos.append(ino)
            m.close(BACKGROUND, ino)
    n = len(inos)
    results["file_create_per_s"] = round(n / (time.perf_counter() - t0), 1)

    t0 = time.perf_counter()
    for ino in inos:
        m.getattr(BACKGROUND, ino)
    results["file_stat_per_s"] = round(n / (time.perf_counter() - t0), 1)

    if getattr(args, "via_vfs", False):
        # Same stats through the VFS entry/attr TTL cache (VERDICT r2 #6):
        # cold pass pays the meta RTT and populates; warm pass shows the
        # cached rate kernels/gateways see on repeated stats.
        from ..chunk import CachedStore, ChunkConfig
        from ..object import create_storage
        from ..vfs import VFS, VFSConfig

        v = VFS(m, CachedStore(create_storage("mem://"), ChunkConfig()),
                VFSConfig(attr_timeout=5.0, entry_timeout=5.0))
        t0 = time.perf_counter()
        for ino in inos:
            v.getattr(BACKGROUND, ino)
        results["vfs_stat_cold_per_s"] = round(n / (time.perf_counter() - t0), 1)
        t0 = time.perf_counter()
        for ino in inos:
            v.getattr(BACKGROUND, ino)
        results["vfs_stat_warm_per_s"] = round(n / (time.perf_counter() - t0), 1)

    t0 = time.perf_counter()
    for dino in dirs:
        m.readdir(BACKGROUND, dino, want_attr=True)
    results["readdir_per_s"] = round(args.dirs / (time.perf_counter() - t0), 1)

    t0 = time.perf_counter()
    for dino in dirs:
        for f in range(args.files):
            m.unlink(BACKGROUND, dino, f"f{f}".encode(), skip_trash=True)
    results["file_unlink_per_s"] = round(n / (time.perf_counter() - t0), 1)

    m.remove_recursive(BACKGROUND, ROOT_INODE, base_name, skip_trash=True)
    m.close_session()
    print(json.dumps(results))
    return 0
