"""`bench`: filesystem micro-benchmark (reference cmd/bench.go:35-330).

Big-file sequential write/read, small-file write/read, and stat rounds
against a mounted path (any mount — ours or a foreign fs), reporting
MiB/s and files/s like the reference's pretty table.
"""

from __future__ import annotations

import json
import os
import shutil
import time


def add_parser(sub):
    p = sub.add_parser("bench", help="benchmark a mounted file system")
    p.add_argument("path", help="directory on the mounted volume")
    p.add_argument("--big-file-size", type=int, default=128, help="MiB")
    p.add_argument("--small-file-size", type=int, default=128, help="KiB")
    p.add_argument("--small-file-count", type=int, default=100)
    p.add_argument("--threads", type=int, default=1)
    p.add_argument("--json", action="store_true")
    p.set_defaults(func=run)


def _timeit(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def run(args) -> int:
    base = os.path.join(args.path, f"__bench_{os.getpid()}")
    os.makedirs(base, exist_ok=True)
    results = {}
    try:
        big = os.path.join(base, "bigfile")
        size = args.big_file_size << 20
        buf = os.urandom(1 << 20)

        def write_big():
            with open(big, "wb") as f:
                for _ in range(args.big_file_size):
                    f.write(buf)
                f.flush()
                os.fsync(f.fileno())

        dt = _timeit(write_big)
        results["big_write_MiB_s"] = round(size / (1 << 20) / dt, 2)

        def read_big():
            with open(big, "rb") as f:
                while f.read(1 << 20):
                    pass

        dt = _timeit(read_big)
        results["big_read_MiB_s"] = round(size / (1 << 20) / dt, 2)

        small = os.urandom(args.small_file_size << 10)
        names = [os.path.join(base, f"small_{i}") for i in range(args.small_file_count)]

        def write_small():
            for n in names:
                with open(n, "wb") as f:
                    f.write(small)

        dt = _timeit(write_small)
        results["small_write_files_s"] = round(len(names) / dt, 1)

        def read_small():
            for n in names:
                with open(n, "rb") as f:
                    f.read()

        dt = _timeit(read_small)
        results["small_read_files_s"] = round(len(names) / dt, 1)

        def stat_files():
            for n in names:
                os.stat(n)

        dt = _timeit(stat_files)
        results["stat_files_s"] = round(len(names) / dt, 1)
    finally:
        shutil.rmtree(base, ignore_errors=True)

    if args.json:
        print(json.dumps(results))
    else:
        width = max(len(k) for k in results)
        for k, v in results.items():
            print(f"  {k:<{width}} : {v}")
    return 0
