"""`gc`: garbage-collect leaked objects; TPU content dedup scan.

Reference cmd/gc.go:76-330: scan all slices from meta, list `chunks/`
objects from the store, diff -> leaked/pending, optionally delete.

New TPU-first capability (BASELINE.md north star): `--dedup` streams every
live block through the batched JTH-256 pipeline and reports duplicate
content groups and reclaimable bytes — content addressing the reference
does not have (its gc diffs block *names* only, cmd/gc.go:253-296).
"""

from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor

from ..chunk.cached_store import block_key, parse_block_key
from ..utils import get_logger

logger = get_logger("cmd.gc")


def add_parser(sub):
    p = sub.add_parser("gc", help="collect leaked objects / dedup scan")
    p.add_argument("meta_url")
    p.add_argument("--delete", action="store_true", help="delete leaked objects")
    p.add_argument("--compact", action="store_true", help="compact fragmented chunks")
    p.add_argument("--dedup", action="store_true", help="content-addressed dedup scan")
    p.add_argument("--hash-backend", default=None,
                   help="cpu|xla|pallas (default: volume format hash_backend)")
    p.add_argument("--threads", type=int, default=10)
    p.add_argument("--age", type=float, default=3600.0,
                   help="only treat objects older than this (seconds) as leaked")
    p.add_argument("--dedup-index", default="", help="write content index JSON here")
    p.set_defaults(func=run)


def run(args) -> int:
    from . import build_store, open_meta

    m, fmt = open_meta(args.meta_url)
    store = build_store(fmt, args)
    bs = fmt.block_size * 1024

    if args.compact:
        from ..vfs.compact import compact_all

        n = compact_all(m, store)
        print(f"compacted {n} chunks")

    # live slice -> expected blocks
    slices = m.list_slices()
    live: dict[str, int] = {}
    for ino, slcs in slices.items():
        for s in slcs:
            if s.id == 0 or s.size == 0:
                continue
            n_blocks = (s.size + bs - 1) // bs
            for i in range(n_blocks):
                bsize = min(bs, s.size - i * bs)
                live[block_key(s.id, i, bsize)] = bsize

    # stored objects under chunks/
    import time as _time

    cutoff = _time.time() - args.age
    stored = {}
    recent = set()
    for obj in store.storage.list_all("chunks/"):
        parsed = parse_block_key(obj.key)
        if parsed is not None:
            stored[obj.key] = obj.size
            if obj.mtime > cutoff:
                recent.add(obj.key)

    # An object can be uploaded before its slice commits to meta (the write
    # pipeline is async), so fresh objects are never "leaked" (reference gc
    # skips recent blocks for the same reason).
    leaked = [k for k in stored if k not in live and k not in recent]
    missing = [k for k in live if k not in stored]
    print(
        f"scanned: {len(stored)} objects, {len(live)} live blocks, "
        f"{len(leaked)} leaked, {len(missing)} missing"
    )
    if missing:
        for k in missing[:10]:
            logger.warning("missing block: %s", k)

    if leaked and args.delete:
        with ThreadPoolExecutor(max_workers=args.threads) as pool:
            list(pool.map(store.storage.delete, leaked))
        print(f"deleted {len(leaked)} leaked objects")

    if args.dedup:
        from ..chunk.indexer import pipeline_backend

        backend = args.hash_backend or pipeline_backend(fmt.hash_backend)
        stats = dedup_scan(m, store, live, backend, args.dedup_index, bs,
                           threads=args.threads)
        print(json.dumps(stats))
    return 0


def dedup_scan(meta, store, live: dict[str, int], backend: str,
               index_path: str, block_size: int, threads: int = 8) -> dict:
    """Content-dedup scan over all live blocks.

    Incremental: digests recorded by the write path (meta content index,
    kv.py `B` keys) are trusted as-is; only blocks missing from the index
    are read back and hashed, and their rows are backfilled so the next
    scan is O(new data). Index rows whose slice no longer exists are
    pruned here — the index is advisory and self-healing.

    Object GETs run `threads` deep through the ordered parallel-fetch
    stage (chunk/parallel.py), overlapping storage I/O with TPU hash
    dispatch; results arrive in input order, so digests and index rows
    are byte-identical to the old serial walk.
    """
    import time as _time

    from ..chunk.parallel import FetchStats, fetch_ordered
    from ..tpu.dedup import dedup_digests
    from ..tpu.jth256 import digest_hex
    from ..tpu.pipeline import HashPipeline, PipelineConfig

    t0 = _time.perf_counter()
    # 1. load the persistent index; prune rows for dead slices
    digest_by_key: dict[str, bytes] = {}
    stale: list[tuple[int, int]] = []
    for sid, indx, bsize, digest in meta.scan_block_digests():
        key = block_key(sid, indx, bsize)
        if key in live:
            digest_by_key[key] = digest
        else:
            stale.append((sid, indx))
    if stale:
        meta.delete_block_digests(stale)
    indexed = len(digest_by_key)
    t_index = _time.perf_counter() - t0

    # 2. hash only blocks the write path didn't index; backfill their rows
    missing = [k for k in live if k not in digest_by_key]
    pipe = HashPipeline(
        PipelineConfig(backend=backend, pad_lanes=max(1, block_size // 65536))
    )
    window = max(1, threads)
    fstats = FetchStats()

    def blocks():
        # windowed parallel GETs on the store's download pool, yielded in
        # input order straight into the hash pipeline; a bad block is
        # skipped (and logged by the stage), never aborts the scan
        yield from fetch_ordered(
            missing,
            lambda key: store._load_block(key, live[key], cache_after=False),
            store._rpool, window, on_error="skip", stats=fstats,
        )

    t1 = _time.perf_counter()
    backfill = []
    for key, digest in pipe.hash_stream(blocks()):
        digest_by_key[key] = digest
        sid, indx, bsize = parse_block_key(key)
        backfill.append((sid, indx, bsize, digest))
    t_readhash = _time.perf_counter() - t1
    t2 = _time.perf_counter()
    if backfill:
        meta.set_block_digests(backfill)
    t_meta = _time.perf_counter() - t2

    # 3. duplicate grouping over the full digest set
    t3 = _time.perf_counter()
    keys = list(digest_by_key)
    digests = [digest_by_key[k] for k in keys]
    dup_mask, first_idx = dedup_digests(digests)
    dup_bytes = sum(live[keys[i]] for i, d in enumerate(dup_mask) if d)
    groups: dict[str, list[str]] = {}
    for i, d in enumerate(dup_mask):
        if d:
            groups.setdefault(keys[first_idx[i]], []).append(keys[i])
    t_group = _time.perf_counter() - t3
    if index_path:
        with open(index_path, "w") as f:
            json.dump(
                {keys[i]: digest_hex(digests[i]) for i in range(len(keys))},
                f,
                indent=1,
            )
    total = _time.perf_counter() - t0
    nbytes = sum(live.values())
    from ..object.resilient import resilience_snapshot

    return {
        "blocks": len(keys),
        "bytes": nbytes,
        "from_index": indexed,
        "hashed_now": len(backfill),
        "stale_index_rows_removed": len(stale),
        "duplicate_blocks": int(dup_mask.sum()),
        "duplicate_bytes": int(dup_bytes),
        "dedup_groups": len(groups),
        "backend": backend,
        "fetch_window": window,
        # stage breakdown (VERDICT r3 #2: the bottleneck must be explicit).
        # `get` is WALL time the fetch stage had GETs in flight;
        # `get_threads` is aggregate per-thread GET seconds — their ratio
        # is the achieved I/O overlap factor (ISSUE 2), and `hash` is the
        # read+hash wall not hidden behind the fetch window.
        "seconds": round(total, 3),
        "gibs": round(nbytes / (1 << 30) / total, 3) if total > 0 else 0.0,
        "blocks_per_s": round(len(keys) / total, 1) if total > 0 else 0.0,
        "stage_seconds": {
            "index_load": round(t_index, 3),
            "get": round(fstats.wall, 3),
            "get_threads": round(fstats.seconds, 3),
            "hash": round(max(t_readhash - fstats.wall, 0.0), 3),
            "meta_backfill": round(t_meta, 3),
            "dup_group": round(t_group, 3),
        },
        # retry/hedge/breaker activity during the scan (the GETs run
        # through object/resilient.py): a scan that paid for fault
        # handling must say so next to its throughput numbers
        "resilience": resilience_snapshot(),
    }
