"""`gc`: garbage-collect leaked objects; TPU content dedup scan.

Reference cmd/gc.go:76-330: scan all slices from meta, list `chunks/`
objects from the store, diff -> leaked/pending, optionally delete.

New TPU-first capability (BASELINE.md north star): `--dedup` streams every
live block through the batched JTH-256 pipeline and reports duplicate
content groups and reclaimable bytes — content addressing the reference
does not have (its gc diffs block *names* only, cmd/gc.go:253-296).
"""

from __future__ import annotations

import json

from ..chunk.cached_store import block_key, parse_block_key
from ..qos import IOClass
from ..utils import get_logger

logger = get_logger("cmd.gc")


def add_parser(sub):
    p = sub.add_parser("gc", help="collect leaked objects / dedup scan")
    p.add_argument("meta_url")
    p.add_argument("--delete", action="store_true", help="delete leaked objects")
    p.add_argument("--compact", action="store_true", help="compact fragmented chunks")
    p.add_argument("--dedup", action="store_true", help="content-addressed dedup scan")
    p.add_argument("--hash-backend", default=None,
                   help="cpu|xla|pallas (default: volume format hash_backend)")
    p.add_argument("--threads", type=int, default=10)
    p.add_argument("--age", type=float, default=3600.0,
                   help="only treat objects older than this (seconds) as leaked")
    p.add_argument("--dedup-index", default="", help="write content index JSON here")
    p.set_defaults(func=run)


def run(args) -> int:
    from . import build_store, open_meta

    m, fmt = open_meta(args.meta_url)
    # meta-attached store: dedup-scan reads of PUT-elided blocks resolve
    # through the content-ref plane (ISSUE 5). No indexer: gc backfills
    # digest rows itself through dedup_scan's own pipeline.
    store = build_store(fmt, args, meta=m, with_indexer=False)
    bs = fmt.block_size * 1024

    if args.compact:
        from ..vfs.compact import compact_all

        n = compact_all(m, store)
        print(f"compacted {n} chunks")

    # live slice -> expected blocks
    slices = m.list_slices()
    live: dict[str, int] = {}
    for ino, slcs in slices.items():
        for s in slcs:
            if s.id == 0 or s.size == 0:
                continue
            n_blocks = (s.size + bs - 1) // bs
            for i in range(n_blocks):
                bsize = min(bs, s.size - i * bs)
                live[block_key(s.id, i, bsize)] = bsize

    # stored objects under chunks/
    import time as _time

    cutoff = _time.time() - args.age
    stored = {}
    recent = set()
    for obj in store.storage.list_all("chunks/"):
        parsed = parse_block_key(obj.key)
        if parsed is not None:
            stored[obj.key] = obj.size
            if obj.mtime > cutoff:
                recent.add(obj.key)

    # Inline dedup (ISSUE 5): an elided block has no object of its own —
    # its bytes live under the canonical block of its content ref. The
    # name diff must translate through the alias plane: aliased live
    # blocks are not "missing", and a canonical object is not "leaked"
    # while any live alias still references it.
    try:
        from ..chunk.ingest import alias_map

        aliases = alias_map(m)
        protected = set(aliases.values())
    except Exception as e:
        logger.warning("content-ref scan unavailable: %s", e)
        aliases, protected = {}, set()

    # An object can be uploaded before its slice commits to meta (the write
    # pipeline is async), so fresh objects are never "leaked" (reference gc
    # skips recent blocks for the same reason).
    leaked = [k for k in stored
              if k not in live and k not in recent and k not in protected]
    missing = [k for k in live
               if k not in stored and aliases.get(k, k) not in stored]
    print(
        f"scanned: {len(stored)} objects, {len(live)} live blocks "
        f"({sum(1 for k in live if k in aliases)} deduped), "
        f"{len(leaked)} leaked, {len(missing)} missing"
    )
    if missing:
        for k in missing[:10]:
            logger.warning("missing block: %s", k)

    if leaked and args.delete:
        # BACKGROUND class on the scheduler's bulk lane (ISSUE 6): a gc
        # sweep sharing a process with a mount must not displace reads
        with store.scheduler.executor(
            "bulk", IOClass.BACKGROUND, width=args.threads
        ) as pool:
            list(pool.map(store.storage.delete, leaked))
        print(f"deleted {len(leaked)} leaked objects")

    if args.dedup:
        from ..chunk.indexer import pipeline_backend

        backend = args.hash_backend or pipeline_backend(fmt.hash_backend)
        stats = dedup_scan(m, store, live, backend, args.dedup_index, bs,
                           threads=args.threads)
        # offline complement of the inline ingest stage: repair refcounts
        # left by crash windows, register existing content so future
        # writes elide, and (with --delete) collapse duplicate objects
        # already in the store into aliases
        stats["content_refs"] = reconcile_content_refs(
            m, store, live, stored, collapse=args.delete, age=args.age
        )
        print(json.dumps(stats))
    return 0


def dedup_scan(meta, store, live: dict[str, int], backend: str,
               index_path: str, block_size: int, threads: int = 8) -> dict:
    """Content-dedup scan over all live blocks.

    Incremental: digests recorded by the write path (meta content index,
    kv.py `B` keys) are trusted as-is; only blocks missing from the index
    are read back and hashed, and their rows are backfilled so the next
    scan is O(new data). Index rows whose slice no longer exists are
    pruned here — the index is advisory and self-healing.

    Object GETs run `threads` deep through the ordered parallel-fetch
    stage (chunk/parallel.py), overlapping storage I/O with TPU hash
    dispatch; results arrive in input order, so digests and index rows
    are byte-identical to the old serial walk.
    """
    import time as _time

    from ..chunk.parallel import FetchStats, fetch_ordered
    from ..tpu.dedup import dedup_digests
    from ..tpu.jth256 import digest_hex
    from ..tpu.pipeline import HashPipeline, PipelineConfig

    t0 = _time.perf_counter()
    # 1. load the persistent index; prune rows for dead slices
    digest_by_key: dict[str, bytes] = {}
    stale: list[tuple[int, int]] = []
    for sid, indx, bsize, digest in meta.scan_block_digests():
        key = block_key(sid, indx, bsize)
        if key in live:
            digest_by_key[key] = digest
        else:
            stale.append((sid, indx))
    if stale:
        meta.delete_block_digests(stale)
    indexed = len(digest_by_key)
    t_index = _time.perf_counter() - t0

    # 2. hash only blocks the write path didn't index; backfill their rows
    missing = [k for k in live if k not in digest_by_key]
    pipe = HashPipeline(
        PipelineConfig(backend=backend, pad_lanes=max(1, block_size // 65536))
    )
    window = max(1, threads)
    fstats = FetchStats()

    def blocks():
        # windowed parallel GETs on the store's download pool, yielded in
        # input order straight into the hash pipeline; a bad block is
        # skipped (and logged by the stage), never aborts the scan
        yield from fetch_ordered(
            missing,
            lambda key: store._load_block(key, live[key], cache_after=False),
            store._bulk_pool, window, on_error="skip", stats=fstats,
        )

    t1 = _time.perf_counter()
    backfill = []
    for key, digest in pipe.hash_stream(blocks()):
        digest_by_key[key] = digest
        sid, indx, bsize = parse_block_key(key)
        backfill.append((sid, indx, bsize, digest))
    t_readhash = _time.perf_counter() - t1
    t2 = _time.perf_counter()
    if backfill:
        meta.set_block_digests(backfill)
    t_meta = _time.perf_counter() - t2

    # 3. duplicate grouping over the full digest set
    t3 = _time.perf_counter()
    keys = list(digest_by_key)
    digests = [digest_by_key[k] for k in keys]
    dup_mask, first_idx = dedup_digests(digests)
    dup_bytes = sum(live[keys[i]] for i, d in enumerate(dup_mask) if d)
    groups: dict[str, list[str]] = {}
    for i, d in enumerate(dup_mask):
        if d:
            groups.setdefault(keys[first_idx[i]], []).append(keys[i])
    t_group = _time.perf_counter() - t3
    if index_path:
        with open(index_path, "w") as f:
            json.dump(
                {keys[i]: digest_hex(digests[i]) for i in range(len(keys))},
                f,
                indent=1,
            )
    total = _time.perf_counter() - t0
    nbytes = sum(live.values())
    from ..object.resilient import resilience_snapshot

    return {
        "blocks": len(keys),
        "bytes": nbytes,
        "from_index": indexed,
        "hashed_now": len(backfill),
        "stale_index_rows_removed": len(stale),
        "duplicate_blocks": int(dup_mask.sum()),
        "duplicate_bytes": int(dup_bytes),
        "dedup_groups": len(groups),
        "backend": backend,
        "fetch_window": window,
        # stage breakdown (VERDICT r3 #2: the bottleneck must be explicit).
        # `get` is WALL time the fetch stage had GETs in flight;
        # `get_threads` is aggregate per-thread GET seconds — their ratio
        # is the achieved I/O overlap factor (ISSUE 2), and `hash` is the
        # read+hash wall not hidden behind the fetch window.
        "seconds": round(total, 3),
        "gibs": round(nbytes / (1 << 30) / total, 3) if total > 0 else 0.0,
        "blocks_per_s": round(len(keys) / total, 1) if total > 0 else 0.0,
        "stage_seconds": {
            "index_load": round(t_index, 3),
            "get": round(fstats.wall, 3),
            "get_threads": round(fstats.seconds, 3),
            "hash": round(max(t_readhash - fstats.wall, 0.0), 3),
            "meta_backfill": round(t_meta, 3),
            "dup_group": round(t_group, 3),
        },
        # retry/hedge/breaker activity during the scan (the GETs run
        # through object/resilient.py): a scan that paid for fault
        # handling must say so next to its throughput numbers
        "resilience": resilience_snapshot(),
        # sharding-plane geometry the hash batches ran on (ISSUE 20):
        # device count, mesh axes, and whether the plane degraded to
        # single-device jit
        "shard": pipe.shard_snapshot(),
    }


def reconcile_content_refs(meta, store, live: dict[str, int],
                           stored: dict[str, int],
                           collapse: bool = False,
                           age: float = 3600.0) -> dict:
    """Offline repair + backfill for the content-ref plane (ISSUE 5) —
    the recovery half of the inline ingest dedup contract:

      1. aliases of dead blocks (elide committed, slice never did — the
         crash window between elision and meta commit) are decref'd;
      2. refcounts are pinned to the observed alias count;
      3. dangling aliases (no ref row) self-heal when the block still has
         its own object, and are REPORTED as data loss otherwise;
      4. content already in the store is registered so future writes
         elide against it; with collapse=True duplicate objects are
         rewritten into aliases and deleted (the Venti-style offline
         reclaim the inline stage cannot do retroactively).

    Invariant after this runs: every alias row maps a live block to a
    ref row whose refcount equals its alias count — zero orphaned, zero
    dangling."""
    import time as _time

    stats = {"orphaned_aliases_repaired": 0, "refcounts_fixed": 0,
             "dangling_content_refs": 0, "self_healed_aliases": 0,
             "registered": 0, "collapsed": 0, "collapsed_bytes": 0}

    # 1. orphaned aliases: the block is gone but its ref survived. The
    # age cutoff mirrors the leaked-object diff's `recent` guard: a
    # writer elides (alias committed) BEFORE its slice commits to meta,
    # so a fresh alias absent from `live` is an in-flight acked write,
    # not a crash orphan — repairing it would delete data mid-commit.
    cutoff = _time.time() - age
    aliases = list(meta.scan_content_aliases())
    orphaned = [
        (sid, indx) for (sid, indx), _d, bsize, ts in aliases
        if block_key(sid, indx, bsize) not in live and ts < cutoff
    ]
    if orphaned:
        for disp, canonical in meta.content_decref(orphaned):
            if disp == "last" and canonical is not None:
                ck = block_key(*canonical)
                if ck not in live:
                    try:
                        store.storage.delete(ck)
                    except Exception:
                        pass
        stats["orphaned_aliases_repaired"] = len(orphaned)
        aliases = list(meta.scan_content_aliases())

    # 2/3. refcount vs alias count; dangling aliases
    ref_rows = {d: (canonical, refs)
                for d, canonical, refs in meta.scan_content_refs()}
    alias_count: dict[bytes, int] = {}
    dangling: list[tuple[int, int]] = []
    for (sid, indx), digest, bsize, _ts in aliases:
        if digest in ref_rows:
            alias_count[digest] = alias_count.get(digest, 0) + 1
        elif block_key(sid, indx, bsize) in stored:
            # the block still has its own object: drop the stray alias
            meta.content_delete_aliases([(sid, indx)])
            stats["self_healed_aliases"] += 1
        else:
            dangling.append((sid, indx))
            logger.error("dangling content ref: block %s has no object "
                         "and no canonical", block_key(sid, indx, bsize))
    stats["dangling_content_refs"] = len(dangling)
    for digest, (canonical, refs) in list(ref_rows.items()):
        observed = alias_count.get(digest, 0)
        if observed != refs:
            meta.content_set_refs(digest, observed)
            stats["refcounts_fixed"] += 1
            if observed == 0:
                ck = block_key(*canonical)
                del ref_rows[digest]  # treated as absent below
                if ck not in live:
                    try:
                        store.storage.delete(ck)
                    except Exception:
                        pass

    # 4. backfill: register live content the inline stage never saw, so
    # future duplicate writes elide against it; collapse rewrites
    # already-duplicated objects into aliases and reclaims their bytes
    aliased = {(sid, indx) for (sid, indx), _d, _b, _ts in
               list(meta.scan_content_aliases())}
    groups: dict[bytes, list[tuple[int, int, int]]] = {}
    for sid, indx, bsize, digest in meta.scan_block_digests():
        key = block_key(sid, indx, bsize)
        if key in live and (sid, indx) not in aliased and key in stored:
            groups.setdefault(digest, []).append((sid, indx, bsize))
    register = []
    collapsible: list[tuple[bytes, int, int, int]] = []
    for digest, members in groups.items():
        start = 0
        if digest not in ref_rows:
            sid, indx, bsize = members[0]
            register.append((digest, sid, indx, bsize))
            start = 1
        else:
            # a canonical whose self-alias row went missing shows up here
            # as an unaliased member: it must NEVER be collapsed (deleting
            # it would orphan every alias of the digest)
            canonical = ref_rows[digest][0]
            members = [m for m in members if m != canonical]
            start = 0
        collapsible.extend((digest, *m) for m in members[start:])
    if register:
        meta.content_register(register)
        stats["registered"] = len(register)
    if collapse and collapsible:
        results = meta.content_incref(
            [(d, sid, indx, bsize) for d, sid, indx, bsize in collapsible]
        )
        for (digest, sid, indx, bsize), got in zip(collapsible, results):
            if got is None:
                continue  # ref vanished mid-flight: leave the object alone
            if got == (sid, indx, bsize):
                continue  # we ARE the canonical: never delete its object
            try:
                store.storage.delete(block_key(sid, indx, bsize))
            except Exception:
                pass
            store.cache.remove(block_key(sid, indx, bsize))
            stats["collapsed"] += 1
            stats["collapsed_bytes"] += bsize
    return stats
