"""`objbench`: object-storage functional test + micro-benchmark
(reference cmd/objbench.go:43-900).

Runs the API correctness suite (put/get/range/head/delete/list/multipart
when supported) then measures put/get throughput with a worker pool.
"""

from __future__ import annotations

import json
import os
import time

from ..object import create_storage
from ..object.interface import NotFoundError
from ..qos import IOClass, global_scheduler


def add_parser(sub):
    p = sub.add_parser("objbench", help="test + benchmark an object store")
    p.add_argument("storage_uri", help="e.g. file:///tmp/blobs, mem://")
    p.add_argument("--block-size", type=int, default=4, help="MiB per object")
    p.add_argument("--big-object-size", type=int, default=64, help="total MiB")
    p.add_argument("--small-objects", type=int, default=64)
    p.add_argument("--threads", type=int, default=4)
    p.add_argument("--compress", default="", choices=["", "none", "lz4", "zstd"],
                   help="compress each object in the put path")
    p.add_argument("--hash-backend", default="",
                   help="cpu|xla|pallas: fingerprint each block in the put "
                        "path and report hash MiB/s (BASELINE config #5)")
    p.set_defaults(func=run)


def functional(store) -> list[str]:
    failures = []

    def check(name, cond):
        if not cond:
            failures.append(name)

    key = "objbench/probe"
    store.put(key, b"hello world")
    check("get", bytes(store.get(key)) == b"hello world")
    check("ranged get", bytes(store.get(key, 6, 5)) == b"world")
    check("head size", store.head(key).size == 11)
    check("list", any(o.key == key for o in store.list_all("objbench/")))
    store.put(key, b"")
    check("empty object", bytes(store.get(key)) == b"")
    # multipart API, when the store supports it (reference objbench.go
    # functional suite covers UploadPart/CompleteUpload). "Unsupported" is
    # signalled by returning None; a RAISING create is a real failure.
    up = store.create_multipart_upload(key + ".mp")
    if up is not None:
        parts = [
            store.upload_part(key + ".mp", up.upload_id, i + 1,
                              bytes([i]) * max(up.min_part_size, 1024))
            for i in range(3)
        ]
        store.complete_upload(key + ".mp", up.upload_id, parts)
        want = b"".join(
            bytes([i]) * max(up.min_part_size, 1024) for i in range(3)
        )
        check("multipart", bytes(store.get(key + ".mp")) == want)
        store.delete(key + ".mp")
        up2 = store.create_multipart_upload(key + ".mp2")
        part = store.upload_part(key + ".mp2", up2.upload_id, 1, b"x" * 1024)
        store.abort_upload(key + ".mp2", up2.upload_id)
        # the abort must actually discard the upload: completing it
        # afterwards has to fail, and no object may appear
        try:
            store.complete_upload(key + ".mp2", up2.upload_id, [part])
            aborted = False
        except Exception:
            aborted = True
        try:
            store.get(key + ".mp2")
            exists = True
        except Exception:
            exists = False
        check("multipart abort", aborted and not exists)
    store.delete(key)
    try:
        store.get(key)
        check("get-after-delete", False)
    except NotFoundError:
        pass
    try:
        store.delete(key)  # idempotent delete
    except Exception:
        failures.append("delete-idempotent")
    return failures


def run(args) -> int:
    from ..object.resilient import RetryPolicy, resilient

    # the resilience wrapper is part of every production stack, so the
    # benchmark measures through it (hedging off: a benchmark must not
    # double its own GETs; single attempt: retries would hide tail cost)
    store = resilient(create_storage(args.storage_uri),
                      policy=RetryPolicy(max_attempts=1), hedge=False)
    store.create()
    failures = functional(store)
    if failures:
        print(f"FUNCTIONAL FAILURES: {failures}")
    else:
        print("functional: all checks passed")

    bs = args.block_size << 20
    n = max(1, (args.big_object_size << 20) // bs)
    keys = [f"objbench/big/{i}" for i in range(n)]
    # distinct payloads: identical blocks would make compression and the
    # dedup-style hash stream unrealistically cheap; generated per put so
    # the 10 GiB config never holds the data set in memory
    seed = os.urandom(bs)

    def payload(i: int) -> bytes:
        r = i % bs
        return seed[r:] + seed[:r]

    compressor = None
    if args.compress and args.compress != "none":
        from ..compress import new_compressor

        compressor = new_compressor(args.compress)
    indexer = None
    if args.hash_backend:
        from ..chunk.indexer import BlockIndexer, pipeline_backend

        indexer = BlockIndexer(
            meta=None, backend=pipeline_backend(args.hash_backend), block_size=bs
        )

    def put_one(item):
        """The full write-path block pipeline: fingerprint -> compress ->
        PUT (role-match to chunk/cached_store._put_block)."""
        i, k = item
        data = payload(i)
        if indexer is not None:
            indexer.submit_raw(0, i, bs, data)
        if compressor is not None:
            data = compressor.compress(data)
        store.put(k, data)

    def get_one(k):
        data = bytes(store.get(k))
        if compressor is not None:
            data = compressor.decompress(data, bs)
        return len(data)

    # BACKGROUND class on the scheduler's bulk lane (ISSUE 6): the bench
    # measures the shaped, scheduled object plane — the same path real
    # bulk traffic takes
    with global_scheduler().executor(
        "bulk", IOClass.BACKGROUND, width=args.threads
    ) as pool:
        t0 = time.perf_counter()
        list(pool.map(put_one, enumerate(keys)))
        if indexer is not None:
            indexer.flush()
        put_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        list(pool.map(get_one, keys))
        get_dt = time.perf_counter() - t0
        list(pool.map(store.delete, keys))

    small = os.urandom(128 << 10)
    skeys = [f"objbench/small/{i}" for i in range(args.small_objects)]
    with global_scheduler().executor(
        "bulk", IOClass.BACKGROUND, width=args.threads
    ) as pool:
        t0 = time.perf_counter()
        list(pool.map(lambda k: store.put(k, small), skeys))
        sput_dt = time.perf_counter() - t0
        list(pool.map(store.delete, skeys))

    result = {
        "put_MiB_s": round(n * bs / (1 << 20) / put_dt, 2),
        "get_MiB_s": round(n * bs / (1 << 20) / get_dt, 2),
        "small_put_objs_s": round(len(skeys) / sput_dt, 1),
        "functional_failures": failures,
    }
    if args.compress and args.compress != "none":
        result["compress"] = args.compress
    if indexer is not None:
        result["hash"] = indexer.stats()
        indexer.close()
    print(json.dumps(result))
    return 1 if failures else 0
