"""`mount` / `umount`: serve a volume through the kernel (reference
cmd/mount.go:541, cmd/mount_unix.go).

Foreground by default; -d daemonizes with a supervisor that restarts the
serving child on crash (reference launchMount restart loop
cmd/mount_unix.go:691-757)."""

from __future__ import annotations

import os
import signal
import sys
import time

from ..utils import get_logger

logger = get_logger("cmd.mount")


def add_parser(sub):
    p = sub.add_parser("mount", help="mount a volume")
    p.add_argument("meta_url")
    p.add_argument("mountpoint")
    p.add_argument("-d", "--background", action="store_true")
    p.add_argument("--readonly", action="store_true")
    p.add_argument("--allow-other", action="store_true")
    p.add_argument("--cache-dir", default="", help="colon-separated dirs or 'memory'")
    p.add_argument("--cache-size", default=0, type=int, help="cache size MiB")
    p.add_argument("--writeback", action="store_true")
    p.add_argument("--op-deadline", type=float, default=0,
                   help="object op wall budget in seconds (0 = default 60; "
                        "hung backend calls are abandoned, never pin a "
                        "worker)")
    p.add_argument("--attempt-timeout", type=float, default=0,
                   help="per-attempt object op bound in seconds (default: "
                        "the remaining op deadline)")
    p.add_argument("--no-hedge", action="store_true",
                   help="disable hedged GETs (tail-latency duplicate "
                        "requests after the live p95)")
    p.add_argument("--upload-limit", type=float, default=0,
                   help="bandwidth limit for uploads in Mbps (0 = "
                        "unlimited); charged at the object boundary, so "
                        "retries and hedges count against it (ISSUE 6)")
    p.add_argument("--download-limit", type=float, default=0,
                   help="bandwidth limit for downloads in Mbps (0 = "
                        "unlimited)")
    p.add_argument("--inline-dedup", action="store_true",
                   help="hash outgoing blocks (volume hash_backend, cpu "
                        "default) and skip compress+PUT for content the "
                        "store already holds; overload degrades to plain "
                        "uploads, never blocks writes (ISSUE 5)")
    p.add_argument("--ingest-flush-ms", type=float, default=5.0,
                   help="max time a partial ingest hash batch waits for "
                        "more blocks before flushing (single-block write "
                        "latency bound)")
    p.add_argument("--compress-backend", default="cpu",
                   choices=["cpu", "xla"],
                   help="batched compression plane backend (ISSUE 8): "
                        "cpu fans liblz4 out across the qos slice lane; "
                        "xla adds a device compressibility estimator "
                        "riding the hash plane's packed H2D upload "
                        "(degrades to cpu when no accelerator)")
    p.add_argument("--compress-lanes", type=int, default=0,
                   help="parallel encode lanes for batched compression "
                        "(0 = host cores)")
    p.add_argument("--no-dedup-bypass", action="store_true",
                   help="disable the adaptive elision bypass: always "
                        "hash+lookup every block even when the sampled "
                        "duplicate density is ~zero (ISSUE 8)")
    p.add_argument("--cache-group", default="",
                   help="join this named peer cache group: serve the local "
                        "block cache to peers and read peers' caches before "
                        "the object store (membership via meta sessions)")
    p.add_argument("--group-weight", type=int, default=1,
                   help="ring weight of this member (bigger cache => "
                        "proportionally more of the keyspace)")
    p.add_argument("--group-listen", default="127.0.0.1:0",
                   help="host:port the peer block server binds (port 0 "
                        "auto-picks; the bound address is published in the "
                        "session info)")
    p.add_argument("--max-readahead", type=int, default=8, help="MiB")
    p.add_argument("--no-streaming-read", action="store_true",
                   help="disable the epoch-streaming read path (ISSUE 11): "
                        "handles then keep the block-granularity window "
                        "doubler capped at --max-readahead instead of "
                        "escalating to file-granularity readahead")
    p.add_argument("--streaming-after", type=int, default=16,
                   help="MiB of sustained sequential reads before a "
                        "handle escalates to streaming readahead")
    p.add_argument("--max-streaming", type=int, default=64,
                   help="MiB cap on a streaming handle's readahead window "
                        "(also bounded by the prefetch queue depth)")
    p.add_argument("--attr-cache", type=float, default=1.0,
                   help="attr cache TTL seconds (reference --attr-cache)")
    p.add_argument("--entry-cache", type=float, default=1.0,
                   help="dentry cache TTL seconds (reference --entry-cache)")
    p.add_argument("--dir-entry-cache", type=float, default=1.0,
                   help="readdir snapshot TTL seconds")
    p.add_argument("--attr-cache-ttl", type=float, default=0.0,
                   help="META-layer attr lease TTL seconds (ISSUE 9): "
                        "cached getattr/lookup serve with zero meta round "
                        "trips; remote staleness is bounded by the TTL and "
                        "usually far lower (the heartbeat change feed "
                        "invalidates mid-lease). 0 = passthrough, byte-"
                        "identical to an uncached client")
    p.add_argument("--entry-cache-ttl", type=float, default=0.0,
                   help="META-layer dentry lease TTL seconds (positive + "
                        "bounded negative lookups); 0 disables")
    p.add_argument("--meta-replica", default="",
                   help="host:port of a meta-server read replica (started "
                        "with meta-server --replica-of): read-only point "
                        "reads route there, WATCH transactions stay on the "
                        "primary, and replica lag is guarded by the volume "
                        "change-epoch")
    p.add_argument("--write-batch", action="store_true",
                   help="checkpoint write plane (ISSUE 13): coalesce "
                        "create/slice-commit/setattr bursts into group-"
                        "commit engine transactions with a local overlay "
                        "for read-your-own-creates; fsync/close/rename are "
                        "barriers (acked fsync = durably committed, "
                        "deferred errors surface there). Default off = "
                        "byte-identical per-op writes")
    p.add_argument("--wbatch-flush-ms", type=float, default=3.0,
                   help="max time a batched mutation waits for the group "
                        "commit timer (barriers drain immediately)")
    p.add_argument("--wbatch-prealloc", type=int, default=1024,
                   help="inode ids preallocated per client allocation txn "
                        "while write batching is on (create storms stop "
                        "round-tripping for ids)")
    p.add_argument("--meta-retries", type=int, default=0,
                   help="meta-plane fault contract (ISSUE 14): max "
                        "attempts per engine op. Transient connection "
                        "resets/timeouts and BUSY responses retry with "
                        "jittered deadline-aware backoff; POSIX errnos "
                        "pass through untouched; a failing engine trips "
                        "a circuit breaker with probe-driven recovery "
                        "(heal re-primes the replica epoch floor, "
                        "revives the session, replays the write batch). "
                        "0 (default) = off, byte-identical engine calls")
    p.add_argument("--meta-deadline", type=float, default=15.0,
                   help="wall-clock budget per meta engine op including "
                        "retries (with --meta-retries)")
    p.add_argument("--meta-degraded-max-stale", type=float, default=0,
                   help="while the meta breaker is OPEN, serve EXPIRED "
                        "lease entries up to this many seconds past "
                        "their lease (marked stale-served); 0 = never "
                        "serve stale, degraded reads fail fast EIO. "
                        "Requires --meta-retries > 0 (the breaker lives "
                        "in the fault contract)")
    p.add_argument("--meta-op-limit", type=float, default=0,
                   help="per-tenant meta ops/s (0 = unlimited): token-"
                        "bucket throttling at the meta boundary — graceful "
                        "queuing, never an error (ISSUE 9)")
    p.add_argument("--heartbeat", type=float, default=12.0,
                   help="session heartbeat interval seconds (also the push-"
                        "invalidation exchange cadence)")
    p.add_argument("--metrics", default="",
                   help="host:port for the /metrics endpoint (reference "
                        "exposeMetrics; empty disables, port 0 auto-picks)")
    p.add_argument("--metrics-push", default="",
                   help="Pushgateway URL to PUT metrics to every "
                        "--push-interval seconds (reference metrics push)")
    p.add_argument("--graphite", default="",
                   help="host:port to stream Graphite plaintext metrics to")
    p.add_argument("--push-interval", type=float, default=10.0)
    p.add_argument("--usage-report-url", default="",
                   help="opt in to a daily anonymous usage ping POSTed to "
                        "this operator-owned URL (reference "
                        "pkg/usage/usage.go reports by default; this build "
                        "sends NOTHING unless a URL is given)")
    p.add_argument("--no-usage-report", action="store_true",
                   help="kept for fstab compatibility; reporting is "
                        "already off unless --usage-report-url is set")
    p.add_argument("--takeover", action="store_true",
                   help="seamless upgrade: adopt a running mount's fuse fd, "
                        "open handles, and session (reference passfd.go)")
    p.add_argument("--no-watchdog", action="store_true")
    p.add_argument("--no-kernel-writeback", action="store_true",
                   help="disable the kernel writeback cache (buffered "
                        "writes then pay one FUSE round trip per syscall)")
    p.add_argument("--no-bgjobs", action="store_true",
                   help="disable background maintenance on this mount")
    p.set_defaults(func=run)

    u = sub.add_parser("umount", help="unmount a volume")
    u.add_argument("mountpoint")
    u.add_argument("-f", "--force", action="store_true")
    u.set_defaults(func=run_umount)


def serve(args) -> int:
    from ..fuse import Server
    from ..vfs import VFS, VFSConfig
    from . import build_store, open_meta

    from ..meta import interface as meta_interface
    from ..vfs.backup import BackgroundJobs
    from ..vfs.compact import compact_chunk

    # Validate meta + storage config FIRST: once the predecessor hands
    # over its fd it exits, so a successor that dies during startup would
    # leave the mount with no server at all. The store itself is built
    # only AFTER the handover — CachedStore.__init__ runs writeback
    # staging recovery, which must not race the predecessor's live
    # staging writes in the shared cache directory.
    from . import storage_for

    m, fmt = open_meta(args.meta_url)
    storage_for(fmt)  # raises on a broken storage configuration

    # meta-plane read scaling (ISSUE 9): replica routing is configured
    # AFTER open_meta so the format load itself always reads the primary
    # (a replica still syncing must not fail the mount)
    replica = getattr(args, "meta_replica", "")
    if replica:
        cfg = getattr(getattr(m, "client", None), "configure_replica", None)
        if cfg is not None:
            cfg(replica)
            logger.info("meta read replica: %s", replica)
        else:
            logger.warning("--meta-replica ignored: engine %s has no "
                           "replica routing", m.name())
    m.configure_meta_cache(
        attr_ttl=getattr(args, "attr_cache_ttl", 0.0),
        entry_ttl=getattr(args, "entry_cache_ttl", 0.0),
    )
    if getattr(args, "meta_op_limit", 0):
        m.configure_op_limit(args.meta_op_limit)
    if getattr(args, "meta_retries", 0):
        # meta fault contract (ISSUE 14): configured AFTER the lease
        # cache so degraded mode sees the real LeaseCache instance
        m.configure_meta_retries(
            max_attempts=args.meta_retries,
            deadline=getattr(args, "meta_deadline", 15.0),
            degraded_max_stale=getattr(args, "meta_degraded_max_stale", 0.0))
    elif getattr(args, "meta_degraded_max_stale", 0):
        logger.warning("--meta-degraded-max-stale ignored: the degraded "
                       "ladder lives in the fault contract, which needs "
                       "--meta-retries > 0")
    if getattr(args, "write_batch", False):
        # checkpoint write plane (ISSUE 13): group-commit write batching;
        # engines without nesting transactions force it back off inside
        m.configure_write_batch(
            flush_ms=getattr(args, "wbatch_flush_ms", 3.0),
            inode_prealloc=getattr(args, "wbatch_prealloc", 1024))

    if args.heartbeat <= 0:
        logger.warning("--heartbeat %.1f invalid; using 1s", args.heartbeat)
        args.heartbeat = 1.0
    elif args.heartbeat >= 300:
        # stale-session GC reaps sessions whose beat is older than 300s
        logger.warning("--heartbeat %.1f >= the 300s staleness age; "
                       "capping at 60s so the session is never reaped live",
                       args.heartbeat)
        args.heartbeat = 60.0

    # seamless upgrade (reference cmd/passfd.go): ask the predecessor for
    # its live fuse fd + open-handle state
    takeover = None
    if getattr(args, "takeover", False):
        from ..fuse.passfd import request_takeover

        takeover = request_takeover(args.mountpoint)
        if takeover is None:
            logger.info("no predecessor at %s; fresh mount", args.mountpoint)
    store = build_store(fmt, args, meta=m)
    # cache group (ISSUE 4): start the peer block server BEFORE the
    # session registers, so the published session info already carries the
    # dialable peer_addr; discovery then rides the heartbeat cadence
    peer_srv = None
    if getattr(args, "cache_group", ""):
        from ..cache import CacheGroup, PeerBlockServer

        peer_srv = PeerBlockServer(store, group=args.cache_group)
        peer_addr = peer_srv.start(getattr(args, "group_listen",
                                           "127.0.0.1:0"))
        m.session_extras.update(
            cache_group=args.cache_group, peer_addr=peer_addr,
            group_weight=max(1, getattr(args, "group_weight", 1)),
        )
        store.cache_group = CacheGroup(
            args.cache_group, self_addr=peer_addr, meta=m,
            weight=max(1, getattr(args, "group_weight", 1)),
            refresh_interval=args.heartbeat,
        )
        logger.info("cache group %r: serving on %s",
                    args.cache_group, peer_addr)
    if takeover is not None and takeover[1].get("sid"):
        # inherit the predecessor's session: locks and sustained inodes
        # keyed by sid remain valid across the swap
        m.sid = int(takeover[1]["sid"])
        # ...but the session INFO must be ours: the predecessor's record
        # advertises its (now dead) cache-group peer_addr/pid
        m.update_session_info()
        m.start_heartbeat(args.heartbeat)
    else:
        m.new_session(heartbeat=args.heartbeat)
    vfs = VFS(
        m,
        store,
        VFSConfig(readonly=args.readonly, max_readahead=args.max_readahead << 20,
                  streaming_read=not args.no_streaming_read,
                  streaming_after=args.streaming_after << 20,
                  max_streaming=args.max_streaming << 20,
                  attr_timeout=args.attr_cache, entry_timeout=args.entry_cache,
                  dir_entry_timeout=args.dir_entry_cache),
        fmt,
    )
    # message handlers (reference registerMetaMsg cmd/mount.go:271):
    # zero-ref slices delete their blocks; hot chunks compact in background
    m.on_msg(meta_interface.DELETE_SLICE, lambda sid, size: store.remove(sid, size))
    m.on_msg(
        meta_interface.COMPACT_CHUNK,
        lambda ino, indx: compact_chunk(m, store, ino, indx),
    )
    bg = None
    if not args.no_bgjobs and not args.readonly:
        bg = BackgroundJobs(m, store)
        bg.start()
    metrics_srv = None
    if getattr(args, "metrics", ""):
        from ..metric import MetricsServer

        metrics_srv = MetricsServer.from_addr(args.metrics)
        logger.info("metrics on http://%s:%d/metrics",
                    metrics_srv.host, metrics_srv.port)
    pusher = None
    if getattr(args, "metrics_push", "") or getattr(args, "graphite", ""):
        from ..metric import MetricsPusher, global_registry

        pusher = MetricsPusher(
            global_registry(), interval=args.push_interval,
            pushgateway=args.metrics_push, graphite=args.graphite,
            job=fmt.name,
        )
    usage = None
    report_url = getattr(args, "usage_report_url", "")
    if report_url and not getattr(args, "no_usage_report", False):
        from ..metric.usage import UsageReporter

        usage = UsageReporter(m, fmt, url=report_url)
    srv = Server(vfs, args.mountpoint, fsname=f"juicefs-tpu:{fmt.name}",
                 allow_other=args.allow_other,
                 writeback_cache=not getattr(args, "no_kernel_writeback", False))
    if takeover is not None:
        srv.adopt(takeover[0], takeover[1])
        logger.info("volume %s taken over at %s (%d handles restored)",
                    fmt.name, args.mountpoint,
                    len(takeover[1].get("handles", [])))
    else:
        _clear_stale_mount(args.mountpoint)
        srv.mount()
        logger.info("volume %s mounted at %s", fmt.name, args.mountpoint)
    srv.enable_takeover()  # we may be a future predecessor ourselves
    watchdog_stop = _start_watchdog(args.mountpoint, srv) \
        if not getattr(args, "no_watchdog", False) else None

    def _stop(signum, frame):
        srv.unmount()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    try:
        srv.serve()
    finally:
        if watchdog_stop is not None:
            watchdog_stop.set()
        if metrics_srv is not None:
            metrics_srv.stop()
        if pusher is not None:
            pusher.stop()
        if usage is not None:
            usage.stop()
        if bg is not None:
            bg.stop()
        if srv.handed_over:
            # the successor owns the fd AND the session now: flush local
            # state but leave the mount and session untouched
            logger.info("handover complete; exiting without unmount")
            m.sid = 0  # close_session must not clean the live session
        vfs.close()
        if peer_srv is not None:
            peer_srv.stop()  # stop serving peers before the cache closes
        try:
            store.close()
        except Exception as e:
            logger.warning("store shutdown: %s", e)
        m.close_session()
    return 0


def _clear_stale_mount(mountpoint: str) -> None:
    """A predecessor that died without unmounting leaves the mountpoint in
    'transport endpoint is not connected' state; lazy-unmount it so the
    fresh mount can proceed (reference mount_unix.go stale-mount check)."""
    import errno as _errno
    import subprocess

    try:
        os.stat(mountpoint)
    except OSError as e:
        if e.errno in (_errno.ENOTCONN, _errno.EIO):
            logger.warning("clearing stale mount at %s", mountpoint)
            subprocess.run(["fusermount", "-u", "-z", mountpoint],
                           capture_output=True)


def _start_watchdog(mountpoint: str, srv) -> "threading.Event":
    """Force-exit a wedged mount so the supervisor can restart it
    (reference watchdog cmd/mount_unix.go:126). A probe thread statfs-es
    the mountpoint; the watchdog only requires that SOME probe completed
    recently — a hung FUSE loop stops all probes and trips it."""
    import threading

    stop = threading.Event()
    last_ok = [time.time()]

    def probe():
        while not stop.is_set():
            try:
                os.statvfs(mountpoint)
                last_ok[0] = time.time()
            except OSError:
                pass  # transient; staleness is judged by the watcher
            stop.wait(5.0)

    def watch():
        import subprocess

        while not stop.wait(10.0):
            if srv.handed_over or srv._stop.is_set():
                return
            if srv._paused.is_set():
                # takeover in progress: the loop is intentionally not
                # answering probes; don't shoot it mid-flush
                last_ok[0] = time.time()
                continue
            if time.time() - last_ok[0] > 120.0:
                logger.error("mount unresponsive for 120s; aborting for restart")
                # lazy-unmount first, else the dead connection leaves the
                # mountpoint in ENOTCONN state and the supervisor's fresh
                # worker can never remount over it
                subprocess.run(["fusermount", "-u", "-z", mountpoint],
                               capture_output=True)
                os._exit(17)

    threading.Thread(target=probe, daemon=True, name="watchdog-probe").start()
    threading.Thread(target=watch, daemon=True, name="watchdog").start()
    return stop


def run(args) -> int:
    if not args.background:
        return serve(args)
    # Supervisor daemonization (reference 3-stage mount + restart loop).
    pid = os.fork()
    if pid > 0:
        # parent: wait for the mount to appear, then return
        for _ in range(100):
            if _is_mountpoint(args.mountpoint):
                print(f"mounted at {args.mountpoint} (supervisor pid {pid})")
                return 0
            time.sleep(0.1)
        logger.error("mount did not come up")
        return 1
    # supervisor child
    os.setsid()
    restarts = 0
    while True:
        worker = os.fork()
        if worker == 0:
            sys.exit(serve(args))
        _, status = os.waitpid(worker, 0)
        code = os.waitstatus_to_exitcode(status)
        if code == 0 or restarts > 10:
            os._exit(0)
        restarts += 1
        logger.warning("mount worker died (%s), restart #%d", code, restarts)
        time.sleep(min(restarts, 10))


def _is_mountpoint(path: str) -> bool:
    try:
        return os.stat(path).st_dev != os.stat(os.path.dirname(os.path.abspath(path))).st_dev
    except OSError:
        return False


def run_umount(args) -> int:
    from ..fuse.mount import umount

    umount(args.mountpoint, lazy=args.force)
    return 0
