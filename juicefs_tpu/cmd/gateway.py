"""`gateway` / `webdav`: serve the volume over S3 / WebDAV
(reference cmd/gateway.go, cmd/webdav.go)."""

from __future__ import annotations

import signal
import threading

from ..utils import get_logger

logger = get_logger("cmd.gateway")


def add_parser(sub):
    g = sub.add_parser("gateway", help="serve the volume over the S3 API")
    g.add_argument("meta_url")
    g.add_argument("--address", default="127.0.0.1")
    g.add_argument("--port", type=int, default=9000)
    g.add_argument("--metrics", default="",
                   help="host:port for the /metrics endpoint (empty disables)")
    g.add_argument("--cache-dir", default="")
    g.add_argument("--cache-size", type=int, default=0)
    g.add_argument("--writeback", action="store_true")
    g.add_argument("--access-key", default="", help="SigV4 access key "
                   "(or MINIO_ROOT_USER); auth disabled when empty")
    g.add_argument("--secret-key", default="", help="SigV4 secret key "
                   "(or MINIO_ROOT_PASSWORD)")
    g.add_argument("--tenant-key", action="append", default=[],
                   metavar="ACCESS:SECRET",
                   help="additional SigV4 key pair mapped to its own "
                        "tenant (repeatable; each key gets its own DRR "
                        "fair-queue identity)")
    g.add_argument("--max-inflight", type=int, default=64,
                   help="admission-gate bound: requests past it shed as "
                        "503 SlowDown instead of queueing")
    g.set_defaults(func=run_gateway)

    w = sub.add_parser("webdav", help="serve the volume over WebDAV")
    w.add_argument("meta_url")
    w.add_argument("--address", default="127.0.0.1")
    w.add_argument("--port", type=int, default=9007)
    w.add_argument("--cache-dir", default="")
    w.add_argument("--cache-size", type=int, default=0)
    w.add_argument("--writeback", action="store_true")
    w.set_defaults(func=run_webdav)


def _build_fs(args):
    from ..fs import FileSystem
    from ..vfs import VFS
    from . import build_store, open_meta

    m, fmt = open_meta(args.meta_url)
    m.new_session(heartbeat=12.0)
    vfs = VFS(m, build_store(fmt, args, meta=m), fmt=fmt)
    return FileSystem(vfs), vfs, m


def _serve_forever(vfs, m, server, what: str, port: int, metrics: str = ""):
    stop = threading.Event()

    def _stop(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    metrics_srv = None
    if metrics:
        from ..metric import MetricsServer

        metrics_srv = MetricsServer.from_addr(metrics)
        print(f"metrics on http://{metrics_srv.host}:{metrics_srv.port}/metrics")
    print(f"{what} listening on port {port}")
    stop.wait()
    if metrics_srv is not None:
        metrics_srv.stop()
    server.stop()
    vfs.close()
    m.close_session()
    return 0


def run_gateway(args) -> int:
    import os

    from ..gateway import S3Gateway

    fs, vfs, m = _build_fs(args)
    # credentials: flags, else the MinIO-convention env vars the reference
    # gateway reads (cmd/gateway.go MINIO_ROOT_USER/PASSWORD)
    ak = args.access_key or os.environ.get("MINIO_ROOT_USER", "")
    sk = args.secret_key or os.environ.get("MINIO_ROOT_PASSWORD", "")
    tenant_keys = {}
    for pair in getattr(args, "tenant_key", []):
        tak, _, tsk = pair.partition(":")
        if not tak or not tsk:
            raise SystemExit(f"--tenant-key needs ACCESS:SECRET, got {pair!r}")
        tenant_keys[tak] = tsk
    gw = S3Gateway(
        fs, args.address, args.port, access_key=ak, secret_key=sk,
        tenant_keys=tenant_keys,
        max_inflight=getattr(args, "max_inflight", 64),
    )
    port = gw.start()
    return _serve_forever(vfs, m, gw, "S3 gateway", port,
                          getattr(args, "metrics", ""))


def run_webdav(args) -> int:
    from ..gateway.webdav import WebDAVServer

    fs, vfs, m = _build_fs(args)
    srv = WebDAVServer(fs, args.address, args.port)
    port = srv.start()
    return _serve_forever(vfs, m, srv, "WebDAV", port)
