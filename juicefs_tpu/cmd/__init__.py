"""CLI driver (reference: cmd/, SURVEY.md §2.1).

`python -m juicefs_tpu.cmd <command>` mirrors the reference's 27-subcommand
urfave/cli app (cmd/main.go:61-89). Commands register in COMMANDS; each
module exposes `add_parser(sub)` and a `run(args)`.

Shared plumbing here: open the meta client, load the volume Format, build
the object store with its wrappers (prefix/shard/encrypt — reference
cmd/mount.go:387 NewReloadableStorage), and assemble the chunk store/VFS.
"""

from __future__ import annotations

import argparse
import os
import sys

from ..chunk import CachedStore, ChunkConfig
from ..meta import new_client
from ..meta.types import Format
from ..object import create_storage, sharded, with_prefix
from ..utils import get_logger

logger = get_logger("cmd")


def open_meta(addr: str, **kw):
    m = new_client(addr, **kw)
    fmt = m.load()
    return m, fmt


def storage_for(fmt: Format):
    """Build the blob store stack from a volume Format (reference
    cmd/mount.go:387 + pkg/object wrappers)."""
    bucket = fmt.bucket or ""
    scheme = fmt.storage or "file"
    if fmt.shards > 1:
        stores = [
            create_storage(f"{scheme}://{bucket}{i:02d}") for i in range(fmt.shards)
        ]
        store = sharded(stores)
    else:
        uri = f"{scheme}://{bucket}" if "://" not in bucket else bucket
        store = create_storage(uri)
    # Keep volume objects namespaced like the reference ({name}/ prefix)
    if scheme not in ("mem",):
        store = with_prefix(store, fmt.name + "/")
    if fmt.encrypt_key:
        from ..object import new_encrypted

        # encrypt_algo selects the body cipher (aes256gcm-rsa default,
        # aes256ctr-*); the key side (RSA-OAEP vs ECIES) follows the PEM
        store = new_encrypted(store, fmt.encrypt_key.encode(),
                              algo=fmt.encrypt_algo or "aes256gcm")
    return store


def chunk_conf(fmt: Format, args=None) -> ChunkConfig:
    cache_dirs = ("memory",)
    writeback = False
    if args is not None:
        if getattr(args, "cache_dir", None):
            cache_dirs = tuple(str(args.cache_dir).split(":"))
        writeback = bool(getattr(args, "writeback", False))
    conf = ChunkConfig(
        block_size=fmt.block_size * 1024,
        compress=fmt.compression,
        cache_dirs=cache_dirs,
        writeback=writeback,
    )
    if getattr(args, "cache_size", None):
        conf.cache_size = int(args.cache_size) << 20
    # NOTE (ISSUE 6 satellite): `--threads` used to silently raise
    # conf.max_download here, mutating the process-wide download pool.
    # Command concurrency now routes through the unified scheduler's
    # BACKGROUND class instead — build_store widens the download/bulk
    # lanes to the command's width without touching foreground config.
    # bandwidth shaping (qos/limiter.py): CLI limits are Mbps, the
    # config carries bytes/s
    if getattr(args, "upload_limit", None):
        conf.upload_limit = float(args.upload_limit) * 1e6 / 8
    if getattr(args, "download_limit", None):
        conf.download_limit = float(args.download_limit) * 1e6 / 8
    # object-plane resilience knobs (object/resilient.py)
    if getattr(args, "op_deadline", None):
        conf.op_deadline = float(args.op_deadline)
    if getattr(args, "attempt_timeout", None):
        conf.attempt_timeout = float(args.attempt_timeout)
    if getattr(args, "no_hedge", False):
        conf.hedge = False
    # batched compression plane + elision bypass (ISSUE 8)
    if getattr(args, "compress_backend", None):
        conf.compress_backend = str(args.compress_backend)
    if getattr(args, "compress_lanes", None):
        conf.compress_lanes = int(args.compress_lanes)
    if getattr(args, "no_dedup_bypass", False):
        conf.dedup_bypass = False
    return conf


def build_store(fmt: Format, args=None, meta=None,
                with_indexer: bool = True) -> CachedStore:
    """Assemble the chunk store; with `meta` and a volume hash_backend,
    every uploaded block is fingerprinted into the meta content index
    (VERDICT r2 #3: the write-path hashing seam, role-match to the
    reference upload hook pkg/chunk/cached_store.go:371-413).

    Any meta-attached store also gets the content-ref plane (ISSUE 5):
    reads resolve elided blocks through aliases and deletes decref —
    required for correctness on any volume another --inline-dedup client
    may have written to. The ingest elision stage itself is opt-in via
    the mount flag. Read-only admin commands (fsck/gc/warmup) pass
    with_indexer=False: they need alias resolution but never upload, so
    spinning up the fingerprint worker (and possibly an accelerator
    backend) for them would be pure startup cost."""
    conf = chunk_conf(fmt, args)
    store = CachedStore(storage_for(fmt), conf)
    # bulk commands (gc/warmup --threads) run at BACKGROUND class; widen
    # the shared lanes so the command's fetch window can actually go that
    # deep — foreground config (max_download) is left untouched, and the
    # scheduler's class priority keeps any concurrent foreground traffic
    # ahead of the widened background stream (ISSUE 6 satellite)
    threads = int(getattr(args, "threads", 0) or 0)
    if threads > 0:
        store.scheduler.widen("download", threads)
        store.scheduler.widen("bulk", threads)
    if meta is not None:
        from ..chunk.indexer import pipeline_backend
        from ..chunk.ingest import ContentRefs, IngestPipeline

        store.content_refs = ContentRefs(meta)
        if fmt.hash_backend and with_indexer:
            from ..chunk.indexer import BlockIndexer

            store.indexer = BlockIndexer(
                meta=meta,
                backend=pipeline_backend(fmt.hash_backend),
                block_size=conf.block_size,
            )
            conf.fingerprint = store.indexer.submit
        if getattr(args, "inline_dedup", False):
            flush_ms = getattr(args, "ingest_flush_ms", None)
            if flush_ms is None:
                flush_ms = 5.0  # explicit 0 means "flush immediately"
            store.ingest = IngestPipeline(
                store,
                store.content_refs,
                backend=pipeline_backend(fmt.hash_backend),
                flush_timeout=max(0.0, float(flush_ms)) / 1e3,
                bypass=conf.dedup_bypass,
            )
    return store


def main(argv: list[str] | None = None) -> int:
    from . import (
        bench,
        config,
        dump,
        format as format_cmd,
        fsck,
        gateway,
        gc,
        info,
        meta_server,
        mount,
        objbench,
        quota,
        stats,
        sync,
        warmup,
    )

    parser = argparse.ArgumentParser(
        prog="juicefs-tpu",
        description="TPU-native JuiceFS-capability distributed file system",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    for mod in (
        format_cmd, mount, bench, objbench, gc, fsck, sync, dump, warmup,
        info, gateway, stats, quota, meta_server, config,
    ):
        mod.add_parser(sub)
    args = parser.parse_args(argv)
    try:
        return args.func(args) or 0
    except KeyboardInterrupt:
        return 130
    except BrokenPipeError:
        return 0  # output piped into head/less that exited early
    except Exception as e:
        logger.error("%s: %s", args.command, e)
        return 1


def fstab_shim(argv: list[str]) -> list[str]:
    """Translate mount(8) helper arguments into `mount` command args
    (reference cmd/main.go:107-121: /sbin/mount.juicefs shim).

    mount(8) invokes: mount.juicefs SPEC DIR [-sfnv] [-o opt1,opt2...]
    """
    spec, mountpoint = argv[0], argv[1]
    out = ["mount", spec, mountpoint]
    it = iter(argv[2:])
    for a in it:
        if a == "-o":
            for opt in next(it, "").split(","):
                if not opt or opt in ("rw", "defaults", "auto", "noauto",
                                      "user", "nouser", "exec", "noexec",
                                      "suid", "nosuid", "dev", "nodev",
                                      "_netdev"):
                    continue
                if opt == "ro":
                    out.append("--readonly")
                elif opt == "background":
                    out.append("-d")
                elif "=" in opt:
                    k, v = opt.split("=", 1)
                    out += [f"--{k.replace('_', '-')}", v]
                else:
                    out.append(f"--{opt.replace('_', '-')}")
        # -s/-f/-n/-v from mount(8) have no meaning here: ignore
    if "-d" not in out:
        out.append("-d")  # fstab mounts must daemonize
    return out


def cli_entry() -> None:
    if os.path.basename(sys.argv[0]).startswith("mount.") and len(sys.argv) >= 3:
        sys.exit(main(fstab_shim(sys.argv[1:])))
    sys.exit(main())
