"""`sync`: bulk object copy between stores (reference pkg/sync + cmd/sync.go).

Producer/consumer layout mirroring the reference: both sides stream sorted
listings, an ordered-merge diff decides what to copy/delete (sync.go:777),
a worker pool moves the objects (worker :616), include/exclude rules filter
keys (:881-1076), and --check-new/--check-all byte-compare contents
(doCheckSum :232 — here via JTH-256 digests instead of raw byte compare).
"""

from __future__ import annotations

import fnmatch
import json
import time
from concurrent.futures import ThreadPoolExecutor

from ..object import create_storage
from ..utils import get_logger

logger = get_logger("cmd.sync")


def add_parser(sub):
    p = sub.add_parser("sync", help="sync objects between two stores")
    p.add_argument("src")
    p.add_argument("dst")
    p.add_argument("--start", default="", help="first key (inclusive)")
    p.add_argument("--end", default="", help="last key (exclusive)")
    p.add_argument("--threads", type=int, default=10)
    p.add_argument("--update", action="store_true",
                   help="overwrite when src is newer (default: size/name diff)")
    p.add_argument("--force-update", action="store_true")
    p.add_argument("--check-new", action="store_true",
                   help="content-compare objects copied this run")
    p.add_argument("--check-all", action="store_true",
                   help="content-compare every object pair")
    p.add_argument("--delete-dst", action="store_true")
    p.add_argument("--delete-src", action="store_true")
    p.add_argument("--include", action="append", default=[])
    p.add_argument("--exclude", action="append", default=[])
    p.add_argument("--dry", action="store_true")
    p.set_defaults(func=run)


def _match(key: str, includes: list[str], excludes: list[str]) -> bool:
    """Rule filter (reference sync.go:918 matchKey; first match wins)."""
    for pat in excludes:
        if fnmatch.fnmatch(key, pat):
            return False
    if includes:
        return any(fnmatch.fnmatch(key, pat) for pat in includes)
    return True


def _diff(src_iter, dst_iter, args):
    """Ordered-merge diff of two sorted listings (reference produce :777).

    Yields ("copy" | "del-dst" | "del-src" | "check", src_obj, dst_obj).
    """
    def nxt(it):
        return next(it, None)

    s, d = nxt(src_iter), nxt(dst_iter)
    while s is not None or d is not None:
        if d is None or (s is not None and s.key < d.key):
            yield "copy", s, None
            s = nxt(src_iter)
        elif s is None or d.key < s.key:
            if args.delete_dst:
                yield "del-dst", None, d
            d = nxt(dst_iter)
        else:
            if args.force_update:
                yield "copy", s, d
            elif s.size != d.size:
                yield "copy", s, d
            elif args.update and s.mtime > d.mtime:
                yield "copy", s, d
            elif args.check_all:
                yield "check", s, d
            elif args.delete_src:
                yield "del-src", s, None
            s, d = nxt(src_iter), nxt(dst_iter)


def _content_equal(src, dst, key: str) -> bool:
    from .. import native

    return native.jth256(bytes(src.get(key))) == native.jth256(bytes(dst.get(key)))


def run(args) -> int:
    src = create_storage(args.src)
    dst = create_storage(args.dst)
    dst.create()

    stats = {"copied": 0, "copied_bytes": 0, "deleted": 0, "checked": 0,
             "mismatch": 0, "skipped": 0}

    def filtered(store):
        for obj in store.list_all("", args.start):
            if args.end and obj.key >= args.end:
                break
            if _match(obj.key, args.include, args.exclude):
                yield obj

    def do(task):
        op, s, d = task
        try:
            if op == "copy":
                if args.dry:
                    stats["copied"] += 1
                    return
                data = bytes(src.get(s.key))
                dst.put(s.key, data)
                stats["copied"] += 1
                stats["copied_bytes"] += len(data)
                if args.check_new and not _content_equal(src, dst, s.key):
                    stats["mismatch"] += 1
                    logger.error("verify failed after copy: %s", s.key)
                if args.delete_src:
                    src.delete(s.key)
                    stats["deleted"] += 1
            elif op == "del-dst":
                if not args.dry:
                    dst.delete(d.key)
                stats["deleted"] += 1
            elif op == "del-src":
                if not args.dry:
                    src.delete(s.key)
                stats["deleted"] += 1
            elif op == "check":
                stats["checked"] += 1
                if not _content_equal(src, dst, s.key):
                    stats["mismatch"] += 1
                    logger.error("content mismatch: %s", s.key)
        except Exception as e:
            logger.error("%s %s: %s", op, (s or d).key, e)
            stats["skipped"] += 1

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=args.threads) as pool:
        list(pool.map(do, _diff(filtered(src), filtered(dst), args)))
    stats["seconds"] = round(time.perf_counter() - t0, 3)
    print(json.dumps(stats))
    return 1 if stats["mismatch"] else 0
