"""`sync`: bulk object copy between stores (reference pkg/sync + cmd/sync.go).

Producer/consumer layout mirroring the reference: both sides stream sorted
listings, an ordered-merge diff decides what to copy/delete (sync.go:777),
a worker pool moves the objects (worker :616), include/exclude rules filter
keys (:881-1076), and --check-new/--check-all content-compare (doCheckSum
:232 — here a streaming ranged compare, constant memory).

Large objects are partitioned into ranged GET + multipart-upload parts
(reference copyData sync.go:440-587) so a 5 GiB object moves through a
fixed-size buffer instead of resident memory.

Cluster mode (reference pkg/sync/cluster.go:132,237): `--manager-listen`
turns this process into an HTTP task server feeding the ordered diff to
any number of `--worker --manager host:port` processes (launched by the
operator or an external scheduler; the reference bootstraps them via ssh),
which pull task batches, copy with their own store clients, and push
stats back.
"""

from __future__ import annotations

import fnmatch
import json
import threading
import time

from ..object import create_storage
from ..object.resilient import RetryPolicy, resilient
from ..qos import IOClass, global_scheduler
from ..utils import get_logger

logger = get_logger("cmd.sync")


def _open_store(uri: str):
    """Sync endpoints go through the resilience wrapper (ISSUE 3: no
    bare-store escapes): classified retries per object op, per-backend
    breaker.  Hedging stays off — bulk copy already runs `--threads`
    wide, and doubling GETs there is bandwidth, not tail latency.  The
    wall deadline is effectively unbounded: a multi-GiB part on a slow
    link may LEGITIMATELY take many minutes, and the wrapper cannot
    know object sizes — the pre-existing contract (ops run to
    completion, failed objects retry on later passes) stays intact."""
    return resilient(create_storage(uri),
                     policy=RetryPolicy(deadline=7 * 86400.0,
                                        max_attempts=5),
                     hedge=False)

CMP_CHUNK = 8 << 20  # streaming-compare window


def add_parser(sub):
    p = sub.add_parser("sync", help="sync objects between two stores")
    p.add_argument("src")
    p.add_argument("dst")
    p.add_argument("--start", default="", help="first key (inclusive)")
    p.add_argument("--end", default="", help="last key (exclusive)")
    p.add_argument("--threads", type=int, default=10)
    p.add_argument("--update", action="store_true",
                   help="overwrite when src is newer (default: size/name diff)")
    p.add_argument("--force-update", action="store_true")
    p.add_argument("--check-new", action="store_true",
                   help="content-compare objects copied this run")
    p.add_argument("--check-all", action="store_true",
                   help="content-compare every object pair")
    p.add_argument("--delete-dst", action="store_true")
    p.add_argument("--delete-src", action="store_true")
    p.add_argument("--include", action="append", default=[])
    p.add_argument("--exclude", action="append", default=[])
    p.add_argument("--dry", action="store_true")
    p.add_argument("--big-threshold", type=int, default=32,
                   help="MiB; objects at least this big copy via ranged "
                        "multipart parts (reference sync.go:440)")
    p.add_argument("--part-size", type=int, default=8, help="MiB per part")
    p.add_argument("--bwlimit", type=int, default=0,
                   help="aggregate copy bandwidth cap in Mbps (0=unlimited; "
                        "reference sync.go bwlimit token bucket)")
    # cluster mode (reference cluster.go)
    p.add_argument("--manager-listen", default="",
                   help="host:port — serve the diff as an HTTP task queue "
                        "instead of copying locally")
    p.add_argument("--worker", action="store_true",
                   help="pull task batches from --manager and execute them")
    p.add_argument("--manager", default="", help="manager host:port")
    p.add_argument("--worker-hosts", default="",
                   help="comma-separated hosts: the manager BOOTSTRAPS one "
                        "worker per host via --worker-launch (reference "
                        "cluster.go:237 ssh bootstrap)")
    p.add_argument("--worker-launch", default="",
                   help="launch template with {host} and {cmd} placeholders "
                        "run through the shell, e.g. 'ssh {host} {cmd}'; "
                        "default: run {cmd} as a local subprocess")
    p.set_defaults(func=run)


def _match(key: str, includes: list[str], excludes: list[str]) -> bool:
    """Rule filter (reference sync.go:918 matchKey; first match wins)."""
    for pat in excludes:
        if fnmatch.fnmatch(key, pat):
            return False
    if includes:
        return any(fnmatch.fnmatch(key, pat) for pat in includes)
    return True


def _diff(src_iter, dst_iter, args):
    """Ordered-merge diff of two sorted listings (reference produce :777).

    Yields ("copy" | "del-dst" | "del-src" | "check", src_obj, dst_obj).
    """
    def nxt(it):
        return next(it, None)

    s, d = nxt(src_iter), nxt(dst_iter)
    while s is not None or d is not None:
        if d is None or (s is not None and s.key < d.key):
            yield "copy", s, None
            s = nxt(src_iter)
        elif s is None or d.key < s.key:
            if args.delete_dst:
                yield "del-dst", None, d
            d = nxt(dst_iter)
        else:
            if args.force_update:
                yield "copy", s, d
            elif s.size != d.size:
                yield "copy", s, d
            elif args.update and s.mtime > d.mtime:
                yield "copy", s, d
            elif args.check_all:
                yield "check", s, d
            elif args.delete_src:
                yield "del-src", s, None
            s, d = nxt(src_iter), nxt(dst_iter)


def _content_equal(src, dst, key: str, size: int) -> bool:
    """Streaming ranged compare: constant memory for any object size
    (replaces whole-object loads; reference doCheckSum streams too)."""
    if size <= 0:
        return bytes(src.get(key)) == bytes(dst.get(key))
    off = 0
    while off < size:
        n = min(CMP_CHUNK, size - off)
        if bytes(src.get(key, off, n)) != bytes(dst.get(key, off, n)):
            return False
        off += n
    return True


class _TokenBucket:
    """Aggregate bandwidth cap shared by all copy workers
    (reference pkg/sync bwlimit via juju/ratelimit)."""

    def __init__(self, mbps: int):
        self.rate = mbps * 125_000  # bytes/s
        self._avail = float(self.rate)  # 1s burst
        self._last = time.monotonic()
        self._mu = threading.Lock()

    def take(self, nbytes: int) -> None:
        while nbytes > 0:
            with self._mu:
                now = time.monotonic()
                self._avail = min(
                    float(self.rate), self._avail + (now - self._last) * self.rate
                )
                self._last = now
                grant = min(nbytes, self._avail)
                self._avail -= grant
                nbytes -= int(grant)
                if nbytes <= 0:
                    return
                wait = nbytes / self.rate
            time.sleep(min(wait, 0.5))


def _copy_object(src, dst, obj, args, stats) -> None:
    """Move one object; big objects go part-by-part through a fixed buffer
    (reference copyData sync.go:440-587 single-PUT vs UploadPart split)."""
    threshold = args.big_threshold << 20
    part_size = max(1 << 20, args.part_size << 20)
    up = None
    if obj.size >= threshold:
        try:
            up = dst.create_multipart_upload(obj.key)
        except Exception:
            up = None
    if up is None:
        data = bytes(src.get(obj.key))
        dst.put(obj.key, data)
        stats.add("copied_bytes", len(data))
        return
    part_size = max(part_size, up.min_part_size)
    n_parts = (obj.size + part_size - 1) // part_size
    if n_parts > up.max_count:  # few huge parts beat failing outright
        part_size = (obj.size + up.max_count - 1) // up.max_count
        n_parts = (obj.size + part_size - 1) // part_size
    parts = []
    try:
        for i in range(n_parts):
            off = i * part_size
            n = min(part_size, obj.size - off)
            data = bytes(src.get(obj.key, off, n))
            parts.append(dst.upload_part(obj.key, up.upload_id, i + 1, data))
            stats.add("copied_bytes", n)
        dst.complete_upload(obj.key, up.upload_id, parts)
    except BaseException:
        try:
            dst.abort_upload(obj.key, up.upload_id)
        except Exception:
            pass
        raise


def _make_executor(src, dst, args, stats):
    """The per-task state machine shared by local and worker modes."""
    bucket = _TokenBucket(args.bwlimit) if getattr(args, "bwlimit", 0) else None

    def do(task):
        op, s, d = task
        try:
            if op == "copy":
                if args.dry:
                    stats.add("copied")
                else:
                    if bucket is not None:
                        bucket.take(s.size)
                    _copy_object(src, dst, s, args, stats)
                    stats.add("copied")
                    if args.check_new and not _content_equal(
                            src, dst, s.key, s.size):
                        stats.add("mismatch")
                        logger.error("verify failed after copy: %s", s.key)
                    if args.delete_src:
                        src.delete(s.key)
                        stats.add("deleted")
            elif op == "del-dst":
                if not args.dry:
                    dst.delete(d.key)
                stats.add("deleted")
            elif op == "del-src":
                if not args.dry:
                    src.delete(s.key)
                stats.add("deleted")
            elif op == "check":
                stats.add("checked")
                if not _content_equal(src, dst, s.key, s.size):
                    stats.add("mismatch")
                    logger.error("content mismatch: %s", s.key)
            # counted only on full execution: a BaseException (interrupt)
            # skips this, so the manager sees the task as unaccounted
            stats.add("tasks_done")
        except Exception as e:
            logger.error("%s %s: %s", op, (s or d).key, e)
            stats.add("skipped")
            stats.add("tasks_done")

    return do


class _Stats(dict):
    """Counter dict updated concurrently by pool workers; the bare
    `d[k] += 1` read-modify-write loses updates under threads, and a lost
    tasks_done makes the cluster manager report a spurious partial sync."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.lock = threading.Lock()

    def add(self, key: str, n: int = 1) -> None:
        with self.lock:
            self[key] = self.get(key, 0) + n


def _new_stats() -> _Stats:
    # tasks_done counts tasks that ran to completion (including skips):
    # the manager's completion check compares it against dispatched count
    return _Stats({"copied": 0, "copied_bytes": 0, "deleted": 0, "checked": 0,
                   "mismatch": 0, "skipped": 0, "tasks_done": 0})


def run(args) -> int:
    if args.worker:
        return run_worker(args)

    src = _open_store(args.src)
    dst = _open_store(args.dst)
    dst.create()

    def filtered(store):
        for obj in store.list_all("", args.start):
            if args.end and obj.key >= args.end:
                break
            if obj.is_dir:
                continue  # folder markers are not copyable objects
            if _match(obj.key, args.include, args.exclude):
                yield obj

    tasks = _diff(filtered(src), filtered(dst), args)
    if args.manager_listen:
        return run_manager(args, tasks)

    stats = _new_stats()
    do = _make_executor(src, dst, args, stats)
    t0 = time.perf_counter()
    # BACKGROUND class (ISSUE 6): bulk replication yields to any
    # foreground traffic sharing the process and its bandwidth budget
    with global_scheduler().executor(
        "bulk", IOClass.BACKGROUND, width=args.threads
    ) as pool:
        list(pool.map(do, tasks))
    stats["seconds"] = round(time.perf_counter() - t0, 3)
    print(json.dumps(stats))
    return 1 if stats["mismatch"] else 0


# -- cluster mode ----------------------------------------------------------
# Wire protocol (JSON over HTTP, reference gob-over-HTTP cluster.go):
#   POST /fetch {"n": N}   -> {"tasks": [[op, obj|null, obj|null], ...],
#                              "done": bool}   (obj = [key, size, mtime])
#   POST /stats {<stats>}  -> {}

_BATCH = 256


def _obj_wire(o):
    return None if o is None else [o.key, o.size, o.mtime]


def _obj_unwire(v):
    from ..object.interface import Obj

    return None if v is None else Obj(key=v[0], size=v[1], mtime=v[2])


def _launch_workers(args, addr: str, flags: list[str]) -> list:
    """Bootstrap one worker per --worker-hosts entry (reference
    cluster.go:237, which ssh-launches workers).  The launch template gets
    {host} and {cmd}; the default runs {cmd} as a local subprocess — the
    hermetic analog of `ssh localhost {cmd}` — so a single command drives
    a whole localhost cluster end to end."""
    import shlex
    import subprocess
    import sys

    hosts = [h.strip() for h in
             getattr(args, "worker_hosts", "").split(",") if h.strip()]
    if not hosts:
        return []
    worker_argv = ["sync", args.src, args.dst, *flags,
                   "--worker", "--manager", addr,
                   "--threads", str(args.threads)]
    template = getattr(args, "worker_launch", "")
    procs = []
    for host in hosts:
        if template:
            # remote form: the template decides the transport and the
            # remote entrypoint; {cmd} is the bare subcommand string
            shell_cmd = template.format(
                host=host, cmd=shlex.join(worker_argv))
            procs.append(subprocess.Popen(
                shell_cmd, shell=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        else:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "juicefs_tpu.cmd", *worker_argv],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        logger.info("launched worker on %s", host)
    return procs


def _reap_workers(procs: list, timeout: float = 30.0) -> bool:
    """Collect bootstrapped workers; True when any failed (nonzero exit
    or had to be killed) — the manager must not report a clean sync."""
    import subprocess

    failed = False
    for p in procs:
        try:
            rc = p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            rc = -9
        if rc != 0:
            logger.error("bootstrapped worker exited %s", rc)
            failed = True
    return failed


def run_manager(args, tasks) -> int:
    """Serve the ordered diff as a task queue (reference startManager
    cluster.go:132); aggregate worker stats.

    Completion integrity: the manager counts every task it hands out and
    requires the workers' aggregated stats to account for all of them —
    a worker that dies mid-batch (tasks fetched but never reported) turns
    into a nonzero exit, never a silent partial sync. A worker that dies
    without even posting stats is caught by the idle timeout instead of
    hanging the manager forever.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    it = iter(tasks)
    lock = threading.Lock()
    totals = _new_stats()
    done = threading.Event()
    state = {"busy": 0, "dispatched": 0, "exhausted": False,
             "last_activity": time.monotonic()}

    class Handler(BaseHTTPRequestHandler):
        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802
            n = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(n) or b"{}")
            with lock:
                state["last_activity"] = time.monotonic()
            if self.path == "/fetch":
                batch = []
                with lock:
                    for _ in range(min(int(req.get("n", _BATCH)), _BATCH)):
                        t = next(it, None)
                        if t is None:
                            state["exhausted"] = True
                            break
                        batch.append([t[0], _obj_wire(t[1]), _obj_wire(t[2])])
                    state["dispatched"] += len(batch)
                self._json({"tasks": batch, "done": not batch})
            elif self.path == "/stats":
                with lock:
                    for k, v in req.items():
                        if k in totals:
                            totals[k] += v
                    state["busy"] -= 1
                    if state["busy"] <= 0:
                        done.set()
                self._json({})
            elif self.path == "/register":
                with lock:
                    state["busy"] += 1
                self._json({})
            elif self.path == "/ping":
                self._json({})  # worker heartbeat (long in-batch copies)
            else:
                self.send_error(404)

        def log_message(self, *a):
            pass

    host, _, port = args.manager_listen.rpartition(":")
    httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port or 0)), Handler)
    addr = f"{httpd.server_address[0]}:{httpd.server_address[1]}"
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    # the hint must carry every execution flag: a worker missing --dry
    # would really copy, missing --delete-src would skip deletions, etc.
    flags = []
    for f in ("dry", "check_new", "check_all", "delete_src", "delete_dst",
              "update", "force_update"):
        if getattr(args, f):
            flags.append("--" + f.replace("_", "-"))
    flags += ["--big-threshold", str(args.big_threshold),
              "--part-size", str(args.part_size)]
    if args.bwlimit:
        flags += ["--bwlimit", str(args.bwlimit)]  # per-worker cap
    print(json.dumps({"manager": addr,
                      "worker_cmd": f"sync {args.src} {args.dst} "
                                    f"{' '.join(flags)} --worker "
                                    f"--manager {addr}"}), flush=True)
    workers = _launch_workers(args, addr, flags)
    idle_limit = 300.0
    timed_out = False
    while not done.wait(timeout=5.0):
        with lock:
            started = state["busy"] > 0 or state["dispatched"] > 0
            busy = state["busy"]
            idle = time.monotonic() - state["last_activity"]
        if started and idle > idle_limit:
            logger.error("no worker activity for %.0fs; giving up", idle)
            timed_out = True
            break
        if workers and busy <= 0 \
                and all(p.poll() is not None for p in workers):
            if not args.worker_launch:
                # every bootstrapped worker already exited and none is
                # still registered: nothing will ever drain the queue —
                # fail now instead of waiting out the idle limit
                logger.error("all bootstrapped workers exited prematurely")
                timed_out = True
                break
            if state["dispatched"] == 0 \
                    and all(p.returncode != 0 for p in workers):
                # custom template: a detaching launcher (ssh -f, tmux)
                # exiting 0 says nothing about the worker, so the idle
                # limit is the backstop there — but every LAUNCH command
                # failing outright before any work is a dead cluster
                logger.error("every worker launch command failed")
                timed_out = True
                break
    httpd.shutdown()
    httpd.server_close()
    worker_failed = _reap_workers(workers)
    # every dispatched task must come back as a completed task: a worker
    # killed mid-batch reports fewer tasks_done than it fetched.  A
    # bootstrapped worker's nonzero exit matters only when the accounting
    # is ALSO short — a straggler that registered after a fast sibling
    # drained the whole queue (its /register hits a closed manager) must
    # not fail a sync whose every task completed.
    incomplete = (timed_out or not state["exhausted"]
                  or totals["tasks_done"] < state["dispatched"])
    if worker_failed and not incomplete:
        logger.warning("a bootstrapped worker exited nonzero after the "
                       "sync completed (late straggler); result unaffected")
    if incomplete and not timed_out:
        logger.error(
            "workers completed %d of %d dispatched tasks — partial sync",
            totals["tasks_done"], state["dispatched"],
        )
    totals["dispatched"] = state["dispatched"]
    print(json.dumps(totals))
    return 1 if (totals["mismatch"] or incomplete) else 0


def run_worker(args) -> int:
    """Pull task batches from the manager and execute them
    (reference cluster.go:340 fetchJobs / :90 sendStats)."""
    import urllib.request

    if not args.manager:
        logger.error("--worker requires --manager host:port")
        return 2
    base = args.manager if "://" in args.manager else f"http://{args.manager}"

    def post(path, payload):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read() or b"{}")

    src = _open_store(args.src)
    dst = _open_store(args.dst)
    stats = _new_stats()
    do = _make_executor(src, dst, args, stats)
    post("/register", {})
    # heartbeat: a batch of large multipart copies can run far longer than
    # the manager's idle timeout between /fetch posts
    stop_ping = threading.Event()

    def ping():
        while not stop_ping.wait(30.0):
            try:
                post("/ping", {})
            except Exception:
                pass

    pinger = threading.Thread(target=ping, daemon=True)
    pinger.start()
    try:
        with global_scheduler().executor(
            "bulk", IOClass.BACKGROUND, width=args.threads
        ) as pool:
            while True:
                out = post("/fetch", {"n": _BATCH})
                tasks = [
                    (t[0], _obj_unwire(t[1]), _obj_unwire(t[2]))
                    for t in out.get("tasks", [])
                ]
                if tasks:
                    list(pool.map(do, tasks))
                if out.get("done"):
                    break
    finally:
        stop_ping.set()
        post("/stats", stats)
    print(json.dumps(stats))
    return 1 if stats["mismatch"] else 0
