"""`warmup`: pre-fill the local cache for paths (reference cmd/warmup.go +
pkg/vfs/fill.go:57-145 — walk the tree, FillCache every slice).

With `--cache-group` (ISSUE 4) the fill is DISTRIBUTED: each invocation
warms only the blocks this member owns on the group's consistent-hash
ring, so a fleet-wide warmup moves each block from the object store
exactly once instead of once per client — everyone else reads it from
the owner's peer server."""

from __future__ import annotations

from ..meta.context import BACKGROUND
from ..meta.types import TYPE_DIRECTORY, TYPE_FILE
from ..utils import get_logger

logger = get_logger("cmd.warmup")


def add_parser(sub):
    p = sub.add_parser("warmup", help="prefill block cache for paths")
    p.add_argument("meta_url")
    p.add_argument("paths", nargs="+", help="volume-absolute paths, e.g. /data")
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--cache-group", default="",
                   help="distribute the fill across this cache group's "
                        "ring: warm only the blocks THIS member owns")
    p.add_argument("--group-self", default="",
                   help="peer address identifying this member on the ring "
                        "(default: the group session on this hostname)")
    p.set_defaults(func=run)


def fill_paths(m, store, paths: list[str], threads: int = 8,
               group=None) -> tuple[int, int]:
    """Warm every slice under the given paths; returns (files, slices).
    With `group` (a cache.CacheGroup) only ring-owned blocks are fetched.

    Per-slice fills fan out at BACKGROUND class on the scheduler's bulk
    lane (ISSUE 6): warmup is maintenance, and its block loads (nested on
    the download lane) inherit background priority via the ambient-class
    demotion rule — a concurrent foreground reader keeps its p99."""
    from ..qos import IOClass

    files = []

    def walk(ino: int, typ: int) -> None:
        if typ == TYPE_FILE:
            files.append(ino)
            return
        if typ != TYPE_DIRECTORY:
            return
        st, entries = m.readdir(BACKGROUND, ino, want_attr=True)
        if st:
            return
        for e in entries:
            if e.name in (b".", b".."):
                continue
            walk(e.inode, e.attr.typ if e.attr else 0)

    for path in paths:
        st, ino, attr = m.resolve(BACKGROUND, path)
        if st:
            logger.error("resolve %s: errno %d", path, st)
            continue
        walk(ino, attr.typ)

    tasks = []
    for ino in files:
        st, attr = m.getattr(BACKGROUND, ino)
        if st:
            continue
        from ..meta.types import CHUNK_SIZE

        for indx in range((attr.length + CHUNK_SIZE - 1) // CHUNK_SIZE):
            st, slices = m.read_chunk(ino, indx)
            if st:
                continue
            tasks.extend((s.id, s.size) for s in slices if s.id)

    only = group.owns if group is not None else None
    with store.scheduler.executor(
        "bulk", IOClass.BACKGROUND, width=threads
    ) as pool:
        list(pool.map(lambda t: store.fill_cache(*t, only=only), tasks))
    return len(files), len(tasks)


def _group_for(m, name: str, self_addr: str):
    """Build a discovery-backed CacheGroup for a warmup run.  The warmup
    process is not the mount, so its ring identity is the LOCAL mount's
    published peer address — given explicitly or found by hostname."""
    import socket

    from ..cache import CacheGroup

    if not self_addr:
        import time

        host = socket.gethostname()
        now = time.time()
        for s in m.do_list_sessions():
            expire = getattr(s, "expire", 0.0) or 0.0
            if (getattr(s, "cache_group", "") == name
                    and getattr(s, "peer_addr", "")
                    and s.hostname == host
                    and not 0 < expire < now):  # skip stale leftovers —
                # a dead predecessor's record must not become our identity
                self_addr = s.peer_addr
                break
    if not self_addr:
        # without a ring identity, owns() would reject EVERY key (all
        # owners are real peers) and the warmup would silently fetch
        # nothing — degrade to an undistributed fill-all instead
        logger.warning(
            "cache group %r: no member on this host (and no --group-self); "
            "warming every block locally", name)
        return None
    return CacheGroup(name, self_addr=self_addr, meta=m)


def run(args) -> int:
    from . import build_store, open_meta

    m, fmt = open_meta(args.meta_url)
    # meta-attached store: warming PUT-elided blocks needs alias
    # resolution through the content-ref plane (ISSUE 5). No indexer:
    # warmup only reads.
    store = build_store(fmt, args, meta=m, with_indexer=False)
    group = None
    if args.cache_group:
        group = _group_for(m, args.cache_group, args.group_self)
    try:
        nfiles, nslices = fill_paths(m, store, args.paths, args.threads,
                                     group=group)
    finally:
        if group is not None:
            group.close()
    shard = f" (ring shard of group {args.cache_group!r})" \
        if group is not None else ""
    print(f"warmed {nfiles} files / {nslices} slices{shard}")
    return 0
