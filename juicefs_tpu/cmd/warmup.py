"""`warmup`: pre-fill the local cache for paths (reference cmd/warmup.go +
pkg/vfs/fill.go:57-145 — walk the tree, FillCache every slice)."""

from __future__ import annotations

from ..meta.context import BACKGROUND
from ..meta.types import TYPE_DIRECTORY, TYPE_FILE
from ..utils import get_logger

logger = get_logger("cmd.warmup")


def add_parser(sub):
    p = sub.add_parser("warmup", help="prefill block cache for paths")
    p.add_argument("meta_url")
    p.add_argument("paths", nargs="+", help="volume-absolute paths, e.g. /data")
    p.add_argument("--threads", type=int, default=8)
    p.set_defaults(func=run)


def fill_paths(m, store, paths: list[str], threads: int = 8) -> tuple[int, int]:
    """Warm every slice under the given paths; returns (files, slices)."""
    from concurrent.futures import ThreadPoolExecutor

    files = []

    def walk(ino: int, typ: int) -> None:
        if typ == TYPE_FILE:
            files.append(ino)
            return
        if typ != TYPE_DIRECTORY:
            return
        st, entries = m.readdir(BACKGROUND, ino, want_attr=True)
        if st:
            return
        for e in entries:
            if e.name in (b".", b".."):
                continue
            walk(e.inode, e.attr.typ if e.attr else 0)

    for path in paths:
        st, ino, attr = m.resolve(BACKGROUND, path)
        if st:
            logger.error("resolve %s: errno %d", path, st)
            continue
        walk(ino, attr.typ)

    tasks = []
    for ino in files:
        st, attr = m.getattr(BACKGROUND, ino)
        if st:
            continue
        from ..meta.types import CHUNK_SIZE

        for indx in range((attr.length + CHUNK_SIZE - 1) // CHUNK_SIZE):
            st, slices = m.read_chunk(ino, indx)
            if st:
                continue
            tasks.extend((s.id, s.size) for s in slices if s.id)

    with ThreadPoolExecutor(max_workers=threads) as pool:
        list(pool.map(lambda t: store.fill_cache(*t), tasks))
    return len(files), len(tasks)


def run(args) -> int:
    from . import build_store, open_meta

    m, fmt = open_meta(args.meta_url)
    store = build_store(fmt, args)
    nfiles, nslices = fill_paths(m, store, args.paths, args.threads)
    print(f"warmed {nfiles} files / {nslices} slices")
    return 0
