"""`meta-server` — serve the bundled Redis-protocol meta transport.

Runs the wire-compatible Redis-subset server (meta/redis_server.py) so
multiple hosts can share one volume via `redis://host:port/db` meta URLs
without an external Redis deployment (reference: the Redis/TiKV server
the Go engines dial; pkg/meta/redis.go:54-76).
"""

from __future__ import annotations


def add_parser(sub):
    p = sub.add_parser(
        "meta-server",
        help="serve the bundled Redis-protocol metadata transport",
    )
    p.add_argument("--host", default="0.0.0.0", help="bind address")
    p.add_argument("--port", type=int, default=6389, help="bind port")
    p.add_argument("--data", default="",
                   help="append-only file for durability (replayed on "
                        "start, compacted to a snapshot; empty = memory only)")
    p.add_argument("--fsync", default="everysec", choices=["always", "everysec"],
                   help="AOF durability: per-mutation or batched (Redis-style)")
    p.add_argument("--replica-of", default="",
                   help="host:port of a primary meta-server to replicate "
                        "from: this instance SYNCs a snapshot, applies the "
                        "live mutation stream, and serves read-only point "
                        "reads for clients mounted with --meta-replica "
                        "(ISSUE 9)")
    p.set_defaults(func=run)


def run(args) -> int:
    from ..meta.redis_server import RedisServer

    srv = RedisServer(args.host, args.port, data_path=args.data or None,
                      fsync=args.fsync,
                      replica_of=getattr(args, "replica_of", "") or None)
    port = srv.start()
    durable = f" (aof={args.data}, fsync={args.fsync})" if args.data else ""
    role = f" replicating {args.replica_of}" if getattr(args, "replica_of", "") else ""
    print(f"meta-server listening on {args.host}:{port}{durable}{role}",
          flush=True)
    srv.wait()
    return 0
