"""`format`: create/overwrite a volume (reference cmd/format.go).

Writes the Format JSON into the meta engine and smoke-tests the object
store with a put/get/delete round trip, as the reference does.
"""

from __future__ import annotations

from ..meta import new_client
from ..meta.types import Format
from ..utils import get_logger

logger = get_logger("cmd.format")


def add_parser(sub):
    p = sub.add_parser("format", help="format a volume")
    p.add_argument("meta_url", help="meta engine address (sqlite3://..., mem://)")
    p.add_argument("name", help="volume name")
    p.add_argument("--storage", default="file", help="object store scheme")
    p.add_argument("--bucket", default="", help="bucket / base path")
    p.add_argument("--block-size", type=int, default=4096, help="block size KiB")
    p.add_argument("--compress", default="", choices=["", "none", "lz4", "zstd"])
    p.add_argument("--shards", type=int, default=0)
    p.add_argument("--capacity", type=int, default=0, help="capacity GiB (0=unlimited)")
    p.add_argument("--inodes", type=int, default=0)
    p.add_argument("--trash-days", type=int, default=1)
    p.add_argument("--enable-acl", action="store_true",
                   help="enable POSIX ACLs (system.posix_acl_* xattrs)")
    p.add_argument("--hash-backend", default="",
                   choices=["", "none", "cpu", "tpu", "xla", "pallas"],
                   help="fingerprint every written block into the meta "
                        "content index using this hash plane")
    p.add_argument("--encrypt-rsa-key", default="", help="PEM private key path")
    p.add_argument("--force", action="store_true", help="overwrite existing format")
    p.set_defaults(func=run)


def run(args) -> int:
    fmt = Format(
        name=args.name,
        storage=args.storage,
        bucket=args.bucket,
        block_size=args.block_size,
        compression="" if args.compress == "none" else args.compress,
        shards=args.shards,
        capacity=args.capacity << 30,
        inodes=args.inodes,
        trash_days=args.trash_days,
        enable_acl=args.enable_acl,
        hash_backend="" if args.hash_backend == "none" else args.hash_backend,
    )
    if args.encrypt_rsa_key:
        with open(args.encrypt_rsa_key) as f:
            fmt.encrypt_key = f.read()
        fmt.encrypt_algo = "aes256gcm-rsa"

    from . import storage_for

    store = storage_for(fmt)
    store.create()
    # object store smoke test (reference format.go test() round trip)
    probe = "testing/probe"
    store.put(probe, b"juicefs-tpu")
    if bytes(store.get(probe)) != b"juicefs-tpu":
        raise IOError("object storage probe read mismatch")
    store.delete(probe)

    m = new_client(args.meta_url)
    st = m.init(fmt, force=args.force)
    if st != 0:
        logger.error("init meta: errno %d", st)
        return 1
    print(f"volume {args.name} formatted: meta={args.meta_url} "
          f"storage={fmt.storage}://{fmt.bucket} block={fmt.block_size}KiB")
    return 0
