"""`format`: create/overwrite a volume (reference cmd/format.go).

Writes the Format JSON into the meta engine and smoke-tests the object
store with a put/get/delete round trip, as the reference does.
"""

from __future__ import annotations

from ..meta import new_client
from ..meta.types import Format
from ..utils import get_logger

logger = get_logger("cmd.format")


def add_parser(sub):
    p = sub.add_parser("format", help="format a volume")
    p.add_argument("meta_url", help="meta engine address (sqlite3://..., mem://)")
    p.add_argument("name", help="volume name")
    p.add_argument("--storage", default="file", help="object store scheme")
    p.add_argument("--bucket", default="", help="bucket / base path")
    p.add_argument("--block-size", type=int, default=4096, help="block size KiB")
    p.add_argument("--compress", default="", choices=["", "none", "lz4", "zstd"])
    p.add_argument("--shards", type=int, default=0)
    p.add_argument("--capacity", type=int, default=0, help="capacity GiB (0=unlimited)")
    p.add_argument("--inodes", type=int, default=0)
    p.add_argument("--trash-days", type=int, default=1)
    p.add_argument("--enable-acl", action="store_true",
                   help="enable POSIX ACLs (system.posix_acl_* xattrs)")
    p.add_argument("--hash-backend", default="",
                   choices=["", "none", "cpu", "tpu", "xla", "pallas"],
                   help="fingerprint every written block into the meta "
                        "content index using this hash plane")
    p.add_argument("--encrypt-rsa-key", default="",
                   help="PEM private key path (RSA -> OAEP wrap, EC P-256 "
                        "-> ECIES wrap)")
    p.add_argument("--encrypt-algo", default=None,
                   choices=["aes256gcm-rsa", "aes256ctr-rsa"],
                   help="object body cipher (reference encrypt.go variants); "
                        "requires --encrypt-rsa-key")
    p.add_argument("--force", action="store_true", help="overwrite existing format")
    p.set_defaults(func=run)


def run(args) -> int:
    fmt = Format(
        name=args.name,
        storage=args.storage,
        bucket=args.bucket,
        block_size=args.block_size,
        compression="" if args.compress == "none" else args.compress,
        shards=args.shards,
        capacity=args.capacity << 30,
        inodes=args.inodes,
        trash_days=args.trash_days,
        enable_acl=args.enable_acl,
        hash_backend="" if args.hash_backend == "none" else args.hash_backend,
    )
    if args.encrypt_algo and not args.encrypt_rsa_key:
        logger.error("--encrypt-algo has no effect without --encrypt-rsa-key")
        return 1
    if args.encrypt_rsa_key:
        with open(args.encrypt_rsa_key) as f:
            fmt.encrypt_key = f.read()
        fmt.encrypt_algo = args.encrypt_algo or "aes256gcm-rsa"

    from . import storage_for

    store = storage_for(fmt)
    store.create()
    # object store smoke test (reference format.go test() round trip)
    probe = "testing/probe"
    store.put(probe, b"juicefs-tpu")
    if bytes(store.get(probe)) != b"juicefs-tpu":
        raise IOError("object storage probe read mismatch")
    store.delete(probe)

    if fmt.hash_backend in ("tpu", "xla", "pallas"):
        _probe_device_bandwidth(fmt.hash_backend)

    m = new_client(args.meta_url)
    st = m.init(fmt, force=args.force)
    if st != 0:
        logger.error("init meta: errno %d", st)
        return 1
    print(f"volume {args.name} formatted: meta={args.meta_url} "
          f"storage={fmt.storage}://{fmt.bucket} block={fmt.block_size}KiB")
    return 0


def _probe_device_bandwidth(backend: str, probe_mb: int = 16) -> None:
    """Measured host→device sanity probe before opting a volume into a
    device hash backend (VERDICT r3 weak #5): write-path fingerprinting
    streams every block to the accelerator, so a thin host link (e.g. a
    tunneled chip at ~0.05 GiB/s) makes the backend pointless for the
    foreground path. The indexer degrades gracefully (drop + gc backfill),
    but the operator should know at format time."""
    try:
        import time

        import jax
        import numpy as np

        devs = jax.devices()
        if not devs or devs[0].platform == "cpu":
            logger.warning(
                "--hash-backend %s: no accelerator visible (platform=%s); "
                "hashing will run via the portable XLA path on CPU",
                backend, devs[0].platform if devs else "none",
            )
            return
        buf = np.zeros(probe_mb << 20, dtype=np.uint8)
        d = jax.device_put(buf, devs[0])
        d.block_until_ready()  # warm: allocator + any first-use setup
        t0 = time.perf_counter()
        d = jax.device_put(buf, devs[0])
        d.block_until_ready()
        dt = time.perf_counter() - t0
        gibs = probe_mb / 1024 / dt
        if gibs < 1.0:
            logger.warning(
                "--hash-backend %s: host->device bandwidth measured at "
                "%.3f GiB/s (%s) — far below block-write rates, so the "
                "write-path indexer will mostly drop-and-backfill; "
                "consider --hash-backend cpu for this host",
                backend, gibs, devs[0].device_kind,
            )
        else:
            logger.info(
                "hash backend %s: h2d probe %.1f GiB/s on %s",
                backend, gibs, devs[0].device_kind,
            )
    except Exception as e:  # probe must never block formatting
        logger.warning("--hash-backend %s: device probe failed (%s); "
                       "the indexer will fall back gracefully", backend, e)
