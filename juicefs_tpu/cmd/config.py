"""`config`: show or change volume settings (reference cmd/config.go).

The Format record lives in the meta engine; changes here propagate to
every live client through the session refresher's hot-reload check
(meta/base.py _check_reload — reference OnReload interface.go:445).
"""

from __future__ import annotations

import json

from ..utils import get_logger

logger = get_logger("cmd.config")

def add_parser(sub):
    p = sub.add_parser("config", help="show / change volume settings")
    p.add_argument("meta_url")
    p.add_argument("--trash-days", type=int, default=None)
    p.add_argument("--capacity", type=int, default=None, help="GiB (0=unlimited)")
    p.add_argument("--inodes", type=int, default=None, help="0=unlimited")
    p.add_argument("--hash-backend", default=None,
                   choices=["", "none", "cpu", "tpu", "xla", "pallas"])
    import argparse as _argparse

    p.add_argument("--enable-acl", dest="enable_acl", default=None,
                   action=_argparse.BooleanOptionalAction,
                   help="--enable-acl / --no-enable-acl")
    p.set_defaults(func=run)


def run(args) -> int:
    from . import open_meta

    m, fmt = open_meta(args.meta_url)
    changes = {}
    if args.trash_days is not None:
        changes["trash_days"] = args.trash_days
    if args.capacity is not None:
        changes["capacity"] = args.capacity << 30
    if args.inodes is not None:
        changes["inodes"] = args.inodes
    if args.hash_backend is not None:
        changes["hash_backend"] = (
            "" if args.hash_backend == "none" else args.hash_backend
        )
    if args.enable_acl is not None:
        changes["enable_acl"] = args.enable_acl

    if not changes:
        print(fmt.remove_secret().to_json())
        return 0

    for k, v in changes.items():
        setattr(fmt, k, v)
    if "hash_backend" in changes and changes["hash_backend"]:
        # hash_backend is v2-gated: Format.from_json drops an explicit
        # value on v1 records, so the opt-in must bump the version or it
        # silently never takes effect
        fmt.meta_version = max(fmt.meta_version, 2)
    st = m.init(fmt, force=True)  # same-uuid overwrite of the record
    if st:
        print(f"config update: errno {st}")
        return 1
    print(json.dumps({"updated": sorted(changes)}))
    return 0
