"""Metric registry lint (CI check, invoked from the test suite).

Imports every module that registers metrics at import time, then walks the
global registry and fails on:

  - names missing the `juicefs_` prefix (one namespace for every exporter);
  - missing help strings (Grafana/`stats` render them);
  - conflicting duplicate registrations (same name re-registered with a
    different type or label set — the silent first-wins behavior would
    otherwise swallow one of them).

Run directly (`python tools/lint_metrics.py`, exit 1 on problems) or call
`lint()` from a test.
"""

from __future__ import annotations

import os
import sys


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _populate_registry() -> None:
    """Import the modules whose metrics register at import time, and the
    runtime registrations that are cheap to trigger."""
    import juicefs_tpu.cache.group          # noqa: F401  peer hit/miss/ring
    import juicefs_tpu.cache.server         # noqa: F401  peer served counters
    import juicefs_tpu.chunk.cached_store   # noqa: F401  staging gauges
    import juicefs_tpu.chunk.disk_cache     # noqa: F401  disk tier counters
    import juicefs_tpu.chunk.ingest         # noqa: F401  inline-dedup counters
    import juicefs_tpu.chunk.mem_cache      # noqa: F401  cache hit/miss/evict
    import juicefs_tpu.chunk.parallel       # noqa: F401  fetch_inflight gauge
    import juicefs_tpu.chunk.prefetch       # noqa: F401  prefetch effectiveness
    import juicefs_tpu.chunk.singleflight   # noqa: F401  dedup counters
    import juicefs_tpu.metric.trace         # noqa: F401  stage rollup histogram
    import juicefs_tpu.object.metered       # noqa: F401  per-backend op meters
    import juicefs_tpu.object.resilient     # noqa: F401  retry/hedge/breaker
    import juicefs_tpu.object.sharding      # noqa: F401  shard routing counter
    import juicefs_tpu.qos.limiter          # noqa: F401  bandwidth throttling
    import juicefs_tpu.qos.scheduler        # noqa: F401  scheduler classes
    import juicefs_tpu.tpu.pipeline         # noqa: F401  batch metrics
    from juicefs_tpu.metric import register_process_metrics

    register_process_metrics()


def lint(registry=None) -> list[str]:
    """Return a list of problems (empty = clean). With an explicit
    registry, lint it as-is; only the global registry needs the
    metric-registering modules imported first."""
    from juicefs_tpu.metric import global_registry

    if registry is None:
        _populate_registry()
    reg = registry or global_registry()
    problems: list[str] = []
    for m in reg.walk():
        if not m.name.startswith("juicefs_"):
            problems.append(f"{m.name}: metric name lacks the juicefs_ prefix")
        if not m.help.strip():
            problems.append(f"{m.name}: missing help string")
        if m.kind not in ("counter", "gauge", "histogram"):
            problems.append(f"{m.name}: unknown metric kind {m.kind!r}")
    problems.extend(reg.conflicts)
    return problems


# the cache-group registry contract (ISSUE 4): the subsystem's metrics all
# live under ONE prefix, and these series are load-bearing (tests and the
# BENCHMARKS table counter-assert them) — a rename must fail CI, not
# silently zero a dashboard
CACHE_GROUP_PREFIX = "juicefs_cache_group_"
CACHE_GROUP_EXPECTED = {
    "juicefs_cache_group_peer_hits",
    "juicefs_cache_group_peer_misses",
    "juicefs_cache_group_peer_errors",
    "juicefs_cache_group_ring_size",
    "juicefs_cache_group_peer_get_seconds",
    "juicefs_cache_group_served",
    "juicefs_cache_group_served_bytes",
    "juicefs_cache_group_serve_misses",
}


def lint_cache_group(registry=None) -> list[str]:
    """Pin the juicefs_cache_group_* registry: every expected series
    exists, and no stray metric squats under the prefix unreviewed."""
    from juicefs_tpu.metric import global_registry

    if registry is None:
        _populate_registry()
    reg = registry or global_registry()
    names = {m.name for m in reg.walk()}
    problems = [
        f"{name}: cache-group metric missing from the registry"
        for name in sorted(CACHE_GROUP_EXPECTED - names)
    ]
    problems += [
        f"{name}: unreviewed metric under {CACHE_GROUP_PREFIX} (add it to "
        "CACHE_GROUP_EXPECTED in tools/lint_metrics.py)"
        for name in sorted(n for n in names
                           if n.startswith(CACHE_GROUP_PREFIX)
                           and n not in CACHE_GROUP_EXPECTED)
    ]
    return problems


# the ingest registry contract (ISSUE 5): same pinned-set pattern as the
# cache group — the bench and the dedup drills counter-assert these series,
# so a rename must fail CI instead of silently zeroing an elision dashboard
INGEST_PREFIX = "juicefs_ingest_"
INGEST_EXPECTED = {
    "juicefs_ingest_blocks",
    "juicefs_ingest_bytes",
    "juicefs_ingest_put_elided",
    "juicefs_ingest_put_elided_bytes",
    "juicefs_ingest_uploaded",
    "juicefs_ingest_passthrough",
    "juicefs_ingest_race_collapsed",
    "juicefs_ingest_errors",
    "juicefs_ingest_queue_blocks",
}


def lint_ingest(registry=None) -> list[str]:
    """Pin the juicefs_ingest_* registry: every expected series exists,
    and no stray metric squats under the prefix unreviewed."""
    from juicefs_tpu.metric import global_registry

    if registry is None:
        _populate_registry()
    reg = registry or global_registry()
    names = {m.name for m in reg.walk()}
    problems = [
        f"{name}: ingest metric missing from the registry"
        for name in sorted(INGEST_EXPECTED - names)
    ]
    problems += [
        f"{name}: unreviewed metric under {INGEST_PREFIX} (add it to "
        "INGEST_EXPECTED in tools/lint_metrics.py)"
        for name in sorted(n for n in names
                           if n.startswith(INGEST_PREFIX)
                           and n not in INGEST_EXPECTED)
    ]
    return problems


def lint_ingest_seam(path: str | None = None) -> list[str]:
    """No-bare-upload check (ISSUE 5): WSlice block uploads must flow
    through the ingest stage when the store has one. Concretely: inside
    `WSlice._upload_block`, every `_put_or_stage` submission must sit
    under an `if` whose test references `ingest` — a refactor that
    reintroduces an unconditional direct upload would silently disable
    elision (writes still succeed, dedup just stops happening), which no
    functional test catches on a low-dup workload."""
    import ast

    path = path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "juicefs_tpu", "chunk", "cached_store.py",
    )
    with open(path) as f:
        tree = ast.parse(f.read())
    fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "WSlice":
            for item in node.body:
                if isinstance(item, ast.FunctionDef) \
                        and item.name == "_upload_block":
                    fn = item
    if fn is None:
        return ["WSlice._upload_block not found in chunk/cached_store.py"]

    parents: dict[int, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node

    def guarded_by_ingest(node) -> bool:
        cur = node
        while id(cur) in parents:
            cur = parents[id(cur)]
            if isinstance(cur, ast.If) and any(
                isinstance(n, (ast.Name, ast.Attribute))
                and (getattr(n, "id", None) == "ingest"
                     or getattr(n, "attr", None) == "ingest")
                for n in ast.walk(cur.test)
            ):
                return True
        return False

    problems = []
    bare = [
        node for node in ast.walk(fn)
        if isinstance(node, ast.Attribute) and node.attr == "_put_or_stage"
        and not guarded_by_ingest(node)
    ]
    for node in bare:
        problems.append(
            f"chunk/cached_store.py:{node.lineno}: WSlice._upload_block "
            "submits _put_or_stage outside an `ingest` guard — block "
            "uploads must flow through the ingest stage when the store "
            "has one"
        )
    # the guard must actually route somewhere: an ingest.submit call
    has_submit = any(
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "submit"
        and isinstance(node.func.value, (ast.Name, ast.Attribute))
        and (getattr(node.func.value, "id", None) == "ingest"
             or getattr(node.func.value, "attr", None) == "ingest")
        for node in ast.walk(fn)
    )
    if not has_submit:
        problems.append(
            "chunk/cached_store.py: WSlice._upload_block never calls "
            "ingest.submit(...) — the inline-dedup seam is gone"
        )
    return problems


# the QoS registry contract (ISSUE 6): the unified scheduler/limiter
# series the chaos drill and the BENCH_r07 mixed-workload bench
# counter-assert — a rename must fail CI, not silently zero a dashboard
QOS_PREFIX = "juicefs_qos_"
QOS_EXPECTED = {
    "juicefs_qos_submitted",
    "juicefs_qos_completed",
    "juicefs_qos_shed",
    "juicefs_qos_wait_seconds",
    "juicefs_qos_queue_depth",
    "juicefs_qos_throttle_wait_seconds",
    "juicefs_qos_throttled_bytes",
}


def lint_qos(registry=None) -> list[str]:
    """Pin the juicefs_qos_* registry: every expected series exists, and
    no stray metric squats under the prefix unreviewed."""
    from juicefs_tpu.metric import global_registry

    if registry is None:
        _populate_registry()
    reg = registry or global_registry()
    names = {m.name for m in reg.walk()}
    problems = [
        f"{name}: qos metric missing from the registry"
        for name in sorted(QOS_EXPECTED - names)
    ]
    problems += [
        f"{name}: unreviewed metric under {QOS_PREFIX} (add it to "
        "QOS_EXPECTED in tools/lint_metrics.py)"
        for name in sorted(n for n in names
                           if n.startswith(QOS_PREFIX)
                           and n not in QOS_EXPECTED)
    ]
    return problems


# pools allowed to exist OUTSIDE the unified scheduler:
#   - qos/ itself (the scheduler's own workers);
#   - object/resilient.py (the elastic abandonment pool: a hung attempt
#     must be abandonable, which a shared bounded worker set cannot do —
#     the ISSUE 6 whitelisted resilience pool).
_QOS_SEAM_WHITELIST = ("qos" + os.sep, os.path.join("object", "resilient.py"))


def lint_qos_seam(root: str | None = None) -> list[str]:
    """No-bare-pool check (ISSUE 6): every concurrency seam in the
    package must ride the unified scheduler.  A module that spins up its
    own ThreadPoolExecutor bypasses priority classes, tenant fairness,
    shedding and the bandwidth budget — exactly the mutually-blind pool
    sprawl the scheduler replaced, and nothing functional would catch the
    regression (the work still completes, QoS just silently stops
    applying to it)."""
    import ast

    root = root or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "juicefs_tpu",
    )
    problems: list[str] = []
    for dirpath, _dirs, files in os.walk(root):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            if any(rel.startswith(w) or rel == w
                   for w in _QOS_SEAM_WHITELIST):
                continue
            with open(path) as f:
                src = f.read()
            if "ThreadPoolExecutor" not in src:
                continue
            for node in ast.walk(ast.parse(src)):
                if not isinstance(node, ast.Call):
                    continue
                name = (getattr(node.func, "id", None)
                        or getattr(node.func, "attr", None))
                if name == "ThreadPoolExecutor":
                    problems.append(
                        f"juicefs_tpu/{rel}:{node.lineno}: bare "
                        "ThreadPoolExecutor outside qos/ — submit through "
                        "the unified scheduler "
                        "(qos.global_scheduler().executor(lane, cls))"
                    )
    return problems


def lint_resilience(root: str | None = None) -> list[str]:
    """Sibling check (ISSUE 3): every `create_storage` consumer inside the
    package must reach the backend through the resilience wrapper — either
    it wraps the store itself (`resilient(...)`) or it hands the store to
    `CachedStore`/`build_store`, which wrap internally.  A module that
    opens a bare store and talks to the backend directly has no deadline,
    no classified retries, and no breaker — exactly the improvised fault
    handling this layer replaced."""
    import ast

    root = root or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "juicefs_tpu",
    )
    problems: list[str] = []
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            if rel.split(os.sep, 1)[0] == "object":
                continue  # the wrapper layer itself
            with open(path) as f:
                src = f.read()
            if "create_storage" not in src:
                continue
            # AST-level on both sides: bare-store detection AND coverage
            # must be real CALLS — a docstring or comment mentioning
            # "CachedStore(" must not satisfy the check
            called = {
                getattr(node.func, "id", None) or getattr(node.func, "attr", None)
                for node in ast.walk(ast.parse(src))
                if isinstance(node, ast.Call)
            }
            if "create_storage" not in called:
                continue
            covered = called & {"resilient", "CachedStore", "build_store"}
            if not covered:
                problems.append(
                    f"juicefs_tpu/{rel}: create_storage() result never "
                    "passes through the resilience wrapper (use "
                    "resilient(...) or CachedStore/build_store)"
                )
    return problems


def main() -> int:
    problems = (lint() + lint_cache_group() + lint_ingest()
                + lint_ingest_seam() + lint_resilience()
                + lint_qos() + lint_qos_seam())
    if problems:
        for p in problems:
            print(f"lint_metrics: {p}", file=sys.stderr)
        return 1
    from juicefs_tpu.metric import global_registry

    print(f"lint_metrics: {len(global_registry().walk())} metrics OK "
          "(+ resilience wiring clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
