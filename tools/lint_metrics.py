"""Compatibility shim over the unified analysis framework (ISSUE 7).

The registry lint and the three seam checks that accreted here across
PRs 1-6 now live in ``tools/analyze/`` (one shared AST walk, one
findings model, one CLI).  This module keeps the historical ``lint*()``
/ CLI contract so existing tests and CI invocations don't break; the
duplicated AST-walking helpers are gone.

Run ``python -m tools.analyze`` for the full analysis (lock-order,
blocking-under-lock, lane-graph, thread lints, seams, registry).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.analyze.core import SourceFile, load_files  # noqa: E402
from tools.analyze.passes import metrics as _metrics  # noqa: E402
from tools.analyze.passes import seams as _seams  # noqa: E402

# re-exported pinned sets (legacy import surface)
CACHE_GROUP_PREFIX = _metrics.CACHE_GROUP_PREFIX
CACHE_GROUP_EXPECTED = _metrics.CACHE_GROUP_EXPECTED
INGEST_PREFIX = _metrics.INGEST_PREFIX
INGEST_EXPECTED = _metrics.INGEST_EXPECTED
QOS_PREFIX = _metrics.QOS_PREFIX
QOS_EXPECTED = _metrics.QOS_EXPECTED
META_WBATCH_PREFIX = _metrics.META_WBATCH_PREFIX
META_WBATCH_EXPECTED = _metrics.META_WBATCH_EXPECTED
COMPRESS_PREFIX = _metrics.COMPRESS_PREFIX
COMPRESS_EXPECTED = _metrics.COMPRESS_EXPECTED
GATEWAY_PREFIX = _metrics.GATEWAY_PREFIX
GATEWAY_EXPECTED = _metrics.GATEWAY_EXPECTED

_PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "juicefs_tpu"
)


def lint(registry=None) -> list[str]:
    """Registry hygiene problems (empty = clean).  With an explicit
    registry, lint it as-is; only the global registry needs the
    metric-registering modules imported first."""
    return _metrics.lint_registry(registry)


def lint_cache_group(registry=None) -> list[str]:
    return _metrics.lint_pinned(CACHE_GROUP_PREFIX, CACHE_GROUP_EXPECTED,
                                "cache-group", registry)


def lint_ingest(registry=None) -> list[str]:
    return _metrics.lint_pinned(INGEST_PREFIX, INGEST_EXPECTED,
                                "ingest", registry)


def lint_qos(registry=None) -> list[str]:
    return _metrics.lint_pinned(QOS_PREFIX, QOS_EXPECTED, "qos", registry)


def lint_wbatch(registry=None) -> list[str]:
    return _metrics.lint_pinned(META_WBATCH_PREFIX, META_WBATCH_EXPECTED,
                                "meta-wbatch", registry)


def lint_compress(registry=None) -> list[str]:
    return _metrics.lint_pinned(COMPRESS_PREFIX, COMPRESS_EXPECTED,
                                "compress", registry)


def lint_gateway(registry=None) -> list[str]:
    return _metrics.lint_pinned(GATEWAY_PREFIX, GATEWAY_EXPECTED,
                                "gateway", registry)


def lint_compress_seam(root: str | None = None) -> list[str]:
    """No-bare-compress check (ISSUE 8), framework-backed."""
    files = load_files(root or _PKG_ROOT)
    return [f.render() for f in _seams.run_compress_seam(files)]


def lint_ingest_seam(path: str | None = None) -> list[str]:
    """No-bare-upload check (ISSUE 5), framework-backed."""
    path = path or os.path.join(_PKG_ROOT, "chunk", "cached_store.py")
    with open(path) as f:
        sf = SourceFile(path, path, f.read())
    return [f.render() for f in _seams.check_ingest_seam(sf)]


def lint_qos_seam(root: str | None = None) -> list[str]:
    """No-bare-pool check (ISSUE 6), framework-backed."""
    files = load_files(root or _PKG_ROOT)
    return [f.render() for f in _seams.run_qos_seam(files)]


def lint_resilience(root: str | None = None) -> list[str]:
    """No-bare-store check (ISSUE 3), framework-backed."""
    files = load_files(root or _PKG_ROOT)
    return [f.render() for f in _seams.run_resilience_seam(files)]


def main() -> int:
    problems = (lint() + lint_cache_group() + lint_ingest()
                + lint_ingest_seam() + lint_resilience()
                + lint_qos() + lint_qos_seam()
                + lint_compress() + lint_compress_seam()
                + lint_wbatch() + lint_gateway())
    if problems:
        for p in problems:
            print(f"lint_metrics: {p}", file=sys.stderr)
        return 1
    from juicefs_tpu.metric import global_registry

    print(f"lint_metrics: {len(global_registry().walk())} metrics OK "
          "(+ resilience wiring clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
