#!/usr/bin/env python3
"""Mutation-testing runner (reference analog: .github/scripts/mutate/,
a go-mutesting wrapper running per-PR changed-line mutation).

Generates first-order mutants of a target module with ast rewrites,
runs a mapped test subset against each, and reports killed/survived.
A surviving mutant is a behavior change no test noticed — either dead
code or a coverage gap.

    python tools/mutate.py juicefs_tpu/meta/slice.py
    python tools/mutate.py juicefs_tpu/vfs/cache.py --max-mutants 20
    python tools/mutate.py --list juicefs_tpu/meta/kv.py

Mutators (classic first-order set):
    cmp   flip comparison operators  (< <-> <=, == <-> !=, > <-> >=)
    bool  swap and/or; drop `not`
    arith +/- swap, *// swap
    const integer off-by-one (skips 0/1-as-index-ish small literals)

Deterministic: mutants are enumerated in source order; --seed with
--max-mutants picks a reproducible subset. Timeout per mutant kills
hangs (an infinite-loop mutant counts as killed). A pre-flight
UNMUTATED run must pass, or every mutant would be reported killed by a
broken test mapping.
"""

from __future__ import annotations

import argparse
import ast
import copy
import os
import subprocess
import sys
import random
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# module-prefix -> fast test subset proving its behavior
TEST_MAP = {
    "juicefs_tpu/meta/slice": ["tests/test_meta.py", "tests/test_fsx.py"],
    "juicefs_tpu/meta/acl": ["tests/test_acl.py"],
    "juicefs_tpu/meta/kv": ["tests/test_meta.py", "tests/test_meta_random.py"],
    "juicefs_tpu/meta/sql": ["tests/test_meta.py", "tests/test_meta_random.py"],
    "juicefs_tpu/vfs/cache": ["tests/test_vfs.py", "tests/test_fuse.py"],
    # ISSUE 11: epoch-streaming read path — the window state machine,
    # reorder tolerance, feedback gating and epoch hook are proven by
    # test_reader.py; test_vfs keeps the end-to-end read semantics honest
    "juicefs_tpu/vfs/reader": ["tests/test_reader.py", "tests/test_vfs.py"],
    "juicefs_tpu/chunk/prefetch": ["tests/test_reader.py",
                                   "tests/test_parallel_fetch.py"],
    "juicefs_tpu/vfs/writer": ["tests/test_vfs.py", "tests/test_fsx.py"],
    "juicefs_tpu/chunk/cached_store": ["tests/test_chunk.py",
                                       "tests/test_chaos.py",
                                       "tests/test_ingest.py"],
    "juicefs_tpu/chunk/ingest": ["tests/test_ingest.py"],
    "juicefs_tpu/tpu/pipeline": ["tests/test_tpu_hash.py",
                                 "tests/test_ingest.py",
                                 "tests/test_tpu_shard.py", "-k",
                                 "not forced_host"],
    # ISSUE 20: the multichip sharding plane. The in-process subset only
    # (forced_host byte-identity tests respawn an 8-device interpreter
    # per case — too slow for a mutant sweep; the in-process tests cover
    # the same mesh through conftest's 8 forced host devices).
    "juicefs_tpu/tpu/sharding": ["tests/test_tpu_shard.py", "-k",
                                 "not forced_host",
                                 "tests/test_tpu_hash.py"],
    "juicefs_tpu/tpu/dedup": ["tests/test_tpu_hash.py",
                              "tests/test_tpu_shard.py", "-k",
                              "not forced_host"],
    "juicefs_tpu/chunk/disk_cache": ["tests/test_chunk.py"],
    "juicefs_tpu/object/resilient": ["tests/test_resilient.py",
                                     "tests/test_chaos.py"],
    "juicefs_tpu/cache/ring": ["tests/test_cache_group.py"],
    "juicefs_tpu/cache/group": ["tests/test_cache_group.py"],
    "juicefs_tpu/cache/server": ["tests/test_cache_group.py"],
    "juicefs_tpu/object/fault": ["tests/test_resilient.py",
                                 "tests/test_chaos.py"],
    "juicefs_tpu/tpu/jth256": ["tests/test_tpu_hash.py"],
    "juicefs_tpu/qos/scheduler": ["tests/test_qos.py"],
    "juicefs_tpu/qos/limiter": ["tests/test_qos.py"],
    # ISSUE 7: the concurrency-contract analyzer and its runtime twin.
    # Fast subset: the seeded-violation fixtures + real-tree gates kill
    # logic mutants without the subprocess CLI round-trips ("-k" args
    # ride the pytest argv); the watchdog drills kill lockwatch mutants.
    "tools/analyze/core": ["tests/test_analysis.py", "-k", "not cli"],
    "tools/analyze/passes/locks": ["tests/test_analysis.py", "-k", "not cli"],
    "tools/analyze/passes/lock_order": ["tests/test_analysis.py",
                                        "-k", "not cli"],
    "tools/analyze/passes/blocking": ["tests/test_analysis.py",
                                      "-k", "not cli"],
    "tools/analyze/passes/lane_graph": ["tests/test_analysis.py",
                                        "-k", "not cli"],
    "tools/analyze/passes/threads": ["tests/test_analysis.py",
                                     "-k", "not cli"],
    "juicefs_tpu/utils/lockwatch": ["tests/test_analysis.py",
                                    "-k", "watchdog"],
    # ISSUE 12: the effect & error-path contract passes and their
    # runtime twin.  Same posture as the ISSUE 7 set: the seeded
    # fixtures + real-tree gates kill logic mutants without subprocess
    # round-trips; the txnwatch drills (non-idempotent closure planted
    # on every engine) kill harness mutants.
    "tools/analyze/passes/effects": ["tests/test_analysis.py",
                                     "-k", "txn_purity or degrade or "
                                           "claim or swallow"],
    "tools/analyze/passes/txn_purity": ["tests/test_analysis.py",
                                        "-k", "txn_purity"],
    "tools/analyze/passes/claims": ["tests/test_analysis.py",
                                    "-k", "claim"],
    "tools/analyze/passes/degrade": ["tests/test_analysis.py",
                                     "-k", "degrade"],
    "tools/analyze/passes/swallow": ["tests/test_analysis.py",
                                     "-k", "swallow"],
    "juicefs_tpu/utils/txnwatch": ["tests/test_analysis.py",
                                   "-k", "txnwatch"],
    # ISSUE 9: meta lease cache + replica read routing. The coherence
    # drills (stale-read bound, negative-entry invalidation, victim
    # invalidation, replica-lag guard, TTL-0 passthrough) live in
    # test_meta_cache.py; redis_kv mutants also face the dist suite's
    # txn-conflict and reconnection drills.
    "juicefs_tpu/meta/cache": ["tests/test_meta_cache.py"],
    "juicefs_tpu/meta/base": ["tests/test_meta.py", "tests/test_meta_cache.py"],
    "juicefs_tpu/meta/redis_kv": ["tests/test_meta_cache.py",
                                  "tests/test_meta_dist.py"],
    "juicefs_tpu/meta/redis_server": ["tests/test_meta_cache.py",
                                      "tests/test_meta_dist.py"],
    # ISSUE 14: meta-plane fault contract — classification, retry/
    # deadline budget, breaker trip/probe/heal, degraded stale-lease
    # serving, replica failover, wbatch absorb/replay, and the FaultyMeta
    # injector's schedule/hang/throttle machinery are drilled there
    "juicefs_tpu/meta/resilient": ["tests/test_meta_fault.py"],
    "juicefs_tpu/meta/fault": ["tests/test_meta_fault.py"],
    # ISSUE 13: checkpoint write plane — group-commit batching, overlay
    # visibility, barrier/sticky-error contract, per-op replay, overload
    # shed, concurrent-writer coalescing are all drilled in test_wbatch
    "juicefs_tpu/meta/wbatch": ["tests/test_wbatch.py"],
    # ISSUE 15: gateway serving plane — admission/shed, range semantics,
    # ordered pagination walker, streaming bounds and tenancy are drilled
    # in test_gateway_plane; the s3 adapter also faces the protocol
    # round-trips in test_fs_gateway and the SigV4 golden vectors
    "juicefs_tpu/gateway/serve": ["tests/test_gateway_plane.py",
                                  "tests/test_golden_signatures.py"],
    "juicefs_tpu/gateway/s3": ["tests/test_gateway_plane.py",
                               "tests/test_fs_gateway.py"],
    # ISSUE 8: batched compression plane + adaptive elision bypass
    "juicefs_tpu/tpu/compress_batch": ["tests/test_compress_batch.py",
                                       "tests/test_tpu_shard.py", "-k",
                                       "not forced_host"],
    "juicefs_tpu/chunk/bypass": ["tests/test_ingest.py", "-k",
                                 "governor or bypass"],
    "juicefs_tpu/compress/__init__": ["tests/test_compress_batch.py"],
}
DEFAULT_TESTS = ["tests/test_meta.py", "tests/test_vfs.py"]

_CMP_FLIP = {ast.Lt: ast.LtE, ast.LtE: ast.Lt, ast.Gt: ast.GtE,
             ast.GtE: ast.Gt, ast.Eq: ast.NotEq, ast.NotEq: ast.Eq}
_ARITH_FLIP = {ast.Add: ast.Sub, ast.Sub: ast.Add,
               ast.Mult: ast.FloorDiv, ast.FloorDiv: ast.Mult}


class _Enumerator(ast.NodeVisitor):
    """Walk the tree once, recording every mutation site."""

    def __init__(self):
        self.sites = []  # (kind, lineno, description, apply_fn_factory)

    def visit_Compare(self, node):
        for i, op in enumerate(node.ops):
            t = type(op)
            if t in _CMP_FLIP:
                self.sites.append((
                    "cmp", node.lineno,
                    f"{t.__name__} -> {_CMP_FLIP[t].__name__}",
                    ("cmp", id(node), i),
                ))
        self.generic_visit(node)

    def visit_BoolOp(self, node):
        t = ast.Or if isinstance(node.op, ast.And) else ast.And
        self.sites.append((
            "bool", node.lineno,
            f"{type(node.op).__name__} -> {t.__name__}",
            ("boolop", id(node), 0),
        ))
        self.generic_visit(node)

    def visit_UnaryOp(self, node):
        if isinstance(node.op, ast.Not):
            self.sites.append((
                "bool", node.lineno, "drop not", ("dropnot", id(node), 0),
            ))
        self.generic_visit(node)

    def visit_BinOp(self, node):
        t = type(node.op)
        if t in _ARITH_FLIP:
            self.sites.append((
                "arith", node.lineno,
                f"{t.__name__} -> {_ARITH_FLIP[t].__name__}",
                ("binop", id(node), 0),
            ))
        self.generic_visit(node)

    def visit_Constant(self, node):
        if isinstance(node.value, int) and not isinstance(node.value, bool) \
                and abs(node.value) > 1:
            self.sites.append((
                "const", node.lineno,
                f"{node.value} -> {node.value + 1}",
                ("const", id(node), 0),
            ))
        self.generic_visit(node)


def _apply(tree, token):
    """Return a mutated DEEP COPY of tree, or None if not applicable."""
    kind, node_id, idx = token
    # map original node ids onto the copy by parallel walk
    clone = copy.deepcopy(tree)
    for orig, new in zip(ast.walk(tree), ast.walk(clone)):
        if id(orig) != node_id:
            continue
        if kind == "cmp":
            t = type(new.ops[idx])
            new.ops[idx] = _CMP_FLIP[t]()
        elif kind == "boolop":
            new.op = ast.Or() if isinstance(new.op, ast.And) else ast.And()
        elif kind == "dropnot":
            _replace_child(clone, new, new.operand)
        elif kind == "binop":
            new.op = _ARITH_FLIP[type(new.op)]()
        elif kind == "const":
            new.value = new.value + 1
        return clone
    return None


def _replace_child(tree, old, new):
    for parent in ast.walk(tree):
        for field, value in ast.iter_fields(parent):
            if value is old:
                setattr(parent, field, new)
                return
            if isinstance(value, list):
                for i, v in enumerate(value):
                    if v is old:
                        value[i] = new
                        return


def run_mutant(path: str, source_tree, token, tests, timeout: float) -> str:
    mutated = _apply(source_tree, token)
    if mutated is None:
        return "skip"
    code = ast.unparse(ast.fix_missing_locations(mutated))
    original = open(path).read()
    try:
        open(path, "w").write(code)
        p = subprocess.run(
            [sys.executable, "-m", "pytest", "-x", "-q", "--no-header",
             "-p", "no:cacheprovider"] + tests,
            cwd=REPO, capture_output=True, timeout=timeout,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        return "survived" if p.returncode == 0 else "killed"
    except subprocess.TimeoutExpired:
        return "killed"  # hang = behavior change noticed
    finally:
        open(path, "w").write(original)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("target", help="module path relative to the repo root")
    ap.add_argument("--max-mutants", type=int, default=0,
                    help="sample at most N mutants (0 = all)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=240.0)
    ap.add_argument("--list", action="store_true",
                    help="only enumerate mutation sites")
    ap.add_argument("--tests", default="",
                    help="comma-separated test files (default: mapped)")
    args = ap.parse_args()

    path = os.path.join(REPO, args.target)
    tree = ast.parse(open(path).read())
    enum = _Enumerator()
    enum.visit(tree)
    sites = enum.sites
    print(f"{args.target}: {len(sites)} mutation sites")
    if args.list:
        for kind, line, desc, _tok in sites:
            print(f"  L{line:5d} [{kind}] {desc}")
        return 0

    if args.tests:
        tests = args.tests.split(",")
    else:
        key = args.target.rsplit(".", 1)[0]
        tests = TEST_MAP.get(key, DEFAULT_TESTS)
    print(f"tests per mutant: {tests}")

    # pre-flight: the UNMUTATED tests must pass (a broken mapping or an
    # already-red suite would report a meaningless 100% kill rate)
    base = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q", "--no-header",
         "-p", "no:cacheprovider"] + tests,
        cwd=REPO, capture_output=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    if base.returncode != 0:
        print(f"baseline run FAILED (pytest rc {base.returncode}) — fix the "
              f"test mapping first:\n{base.stdout.decode()[-800:]}")
        return 2

    chosen = list(range(len(sites)))
    if args.max_mutants and args.max_mutants < len(chosen):
        rng = random.Random(args.seed)
        chosen = sorted(rng.sample(chosen, args.max_mutants))

    killed = survived = 0
    survivors = []
    t0 = time.time()
    for n, i in enumerate(chosen):
        kind, line, desc, tok = sites[i]
        verdict = run_mutant(path, tree, tok, tests, args.timeout)
        if verdict == "killed":
            killed += 1
        elif verdict == "survived":
            survived += 1
            survivors.append((line, kind, desc))
        print(f"[{n+1}/{len(chosen)}] L{line} {kind}: {desc} -> {verdict}")
    dt = time.time() - t0
    total = killed + survived
    score = 100.0 * killed / total if total else 0.0
    print(f"\nmutation score: {score:.0f}% ({killed}/{total} killed, "
          f"{dt:.0f}s)")
    for line, kind, desc in survivors:
        print(f"  SURVIVED L{line} [{kind}] {desc}")
    return 0 if survived == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
