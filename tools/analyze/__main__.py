"""CLI runner: ``python -m tools.analyze``.

Exit 1 on any unsuppressed finding, printed one per line as
``file:line rule: message`` (the CI contract, tests/test_analysis.py).

  --json    machine-readable report (findings, suppressed, stale)
  --stale   ALSO fail (exit 1) on stale suppressions — an allow() whose
            rule no longer fires is a dead justification that will
            silence the NEXT real finding on that line; tier-1 runs
            this mode so stale allows rot out of the tree (ISSUE 12)
  --ast     skip the runtime metric-registry pass (pure-AST mode)
  --root    analyze a different tree (fixtures, tests)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.analyze import DEFAULT_ROOT, analyze  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.analyze")
    ap.add_argument("--root", default=DEFAULT_ROOT)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--stale", action="store_true",
                    help="list stale suppressions (rule no longer fires) "
                         "and exit 1 when any exist")
    ap.add_argument("--ast", action="store_true",
                    help="skip the runtime metric-registry pass")
    args = ap.parse_args(argv)

    report = analyze(root=args.root, runtime=not args.ast)

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in report.findings],
            "suppressed": [
                {"finding": f.as_dict(), "reason": s.reason,
                 "comment_line": s.comment_line}
                for f, s in report.suppressed
            ],
            "stale": [
                {"file": s.file, "line": s.comment_line,
                 "rules": list(s.rules), "reason": s.reason}
                for s in report.stale
            ],
        }, indent=2))
        return 1 if (report.failed
                     or (args.stale and report.stale)) else 0

    for f in report.findings:
        print(f.render(), file=sys.stderr)
    if args.stale:
        for s in report.stale:
            print(f"{s.file}:{s.comment_line} stale-suppression: "
                  f"allow({','.join(s.rules)}) no longer matches a finding "
                  f"(reason was: {s.reason})")
    if report.failed:
        print(f"tools.analyze: {len(report.findings)} unsuppressed "
              f"finding(s)", file=sys.stderr)
        return 1
    if args.stale and report.stale:
        print(f"tools.analyze: {len(report.stale)} stale suppression(s) "
              "— prune the dead allow() comments", file=sys.stderr)
        return 1
    print(f"tools.analyze: OK ({len(report.suppressed)} suppressed, "
          f"{len(report.stale)} stale)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
