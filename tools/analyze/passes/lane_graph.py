"""QoS lane-graph verifier (rule ``lane-graph``).

PR 6 replaced the old pool-split deadlock rules with one convention: the
scheduler's named lanes form an acyclic graph, and a task running ON a
bounded lane never submits-and-waits on its OWN lane (with every worker
parked in waiters, nothing runs the waited-on work).  Until this pass,
that convention lived in prose (docs/ARCHITECTURE.md "Concurrency
model").  Here it becomes checked:

1. every ``X = <sched>.executor("<lane>", <class>)`` site is collected
   (self-attrs, locals, ``with ... as ex``), giving each executor handle
   a lane;
2. every ``E.submit(fn, ...)`` / ``E.map(fn, ...)`` /
   ``fetch_ordered(items, fn, E, ...)`` marks ``fn`` (resolved by unique
   method/function name, lambdas scanned inline) as *running on* E's
   lane, propagated through resolved same-class/module calls;
3. a lane-running function that BLOCKS on another submit
   (``E.submit(...).result()``, a local future's ``.result()``, a
   blocking ``fetch_ordered``/``.map``) contributes a lane edge.

Findings: a worker blocking on its own lane; a cycle in the combined
(discovered + declared) graph; and any DISCOVERED edge missing from
``DECLARED_LANE_EDGES`` below — new cross-lane waits must be declared
here (and stay acyclic) to pass CI, which is exactly the review hook
the prose rule never had.  Dynamic dispatch the static walk cannot see
is covered at runtime by the lock watchdog's holds-while-blocking check.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Pass, SourceFile, attr_chain
from .locks import LockModel, class_id

# The lane dependency graph the architecture allows (ARCHITECTURE.md
# "Concurrency model"): slice-lane work fans block loads out on the
# download lane; bulk commands read segments through the download lane.
# Adding an edge here is a reviewed act; the pass fails on any cycle.
DECLARED_LANE_EDGES: frozenset[tuple[str, str]] = frozenset({
    ("slice", "download"),
    ("bulk", "download"),
})


def _executor_lane(call: ast.AST) -> Optional[str]:
    """Lane name when `call` is `<anything>.executor("<lane>", ...)`."""
    if isinstance(call, ast.Call) and isinstance(call.func, ast.Attribute) \
            and call.func.attr == "executor" and call.args \
            and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


class _Lanes:
    """Executor-handle -> lane tables, plus function lane assignments."""

    def __init__(self, files: list[SourceFile], model: LockModel):
        self.model = model
        self.attr_lanes: dict[str, dict[str, str]] = {}   # cls -> attr -> lane
        self.attr_owner: dict[str, set[str]] = {}          # attr -> classes
        self.local_lanes: dict[str, dict[str, str]] = {}   # qual -> var -> lane
        # function qual -> lanes it runs on (submit targets)
        self.runs_on: dict[str, set[str]] = {}
        # method/function simple name -> quals (unique-name resolution)
        self.by_name: dict[str, list[str]] = {}
        for qual in model.funcs:
            name = qual.rsplit("::", 1)[-1].rsplit(".", 1)[-1].strip("<>")
            self.by_name.setdefault(name, []).append(qual)
        for sf in files:
            if sf.tree is not None:
                self._collect(sf)

    def _collect(self, sf: SourceFile) -> None:
        # class-attr executors
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                cid = class_id(sf, node.name)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        chain = attr_chain(sub.targets[0])
                        lane = _executor_lane(sub.value)
                        if lane and chain and len(chain) == 2 \
                                and chain[0] == "self":
                            self.attr_lanes.setdefault(
                                cid, {})[chain[1]] = lane
                            self.attr_owner.setdefault(
                                chain[1], set()).add(cid)
        # function-local executors (assignments and `with ... as ex`)
        self._collect_locals(sf)

    def _collect_locals(self, sf: SourceFile) -> None:
        def scan_fn(fn, qual):
            table = self.local_lanes.setdefault(qual, {})
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    lane = _executor_lane(node.value)
                    if lane:
                        table[node.targets[0].id] = lane
                elif isinstance(node, ast.With):
                    for item in node.items:
                        lane = _executor_lane(item.context_expr)
                        if lane and isinstance(item.optional_vars, ast.Name):
                            table[item.optional_vars.id] = lane

        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_fn(node, f"{sf.rel}::{node.name}")
            elif isinstance(node, ast.ClassDef):
                cid = class_id(sf, node.name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        scan_fn(item, f"{cid}.{item.name}")

    def lane_of(self, expr: ast.AST, qual: str, cls: Optional[str]
                ) -> Optional[str]:
        """Lane of an executor expression: local var, self-attr, or a
        foreign attr resolved by unique name (`self.store._rpool`)."""
        chain = attr_chain(expr)
        if chain is None:
            return _executor_lane(expr)   # chained: sched.executor(...).x?
        if len(chain) == 1:
            return self.local_lanes.get(qual, {}).get(chain[0])
        if chain[0] == "self" and len(chain) == 2:
            # a self attribute is class-local: resolve against THIS class
            # only (falling through to unique-name here would alias e.g.
            # the resilience layer's own `self._pool` onto CachedStore's)
            if cls is None:
                return None
            return self.attr_lanes.get(cls, {}).get(chain[1])
        owners = self.attr_owner.get(chain[-1], set())
        if len(owners) == 1:
            return self.attr_lanes[next(iter(owners))][chain[-1]]
        return None

    def mark_runs_on(self, fn_expr: ast.AST, lane: str, sf: SourceFile,
                     qual: str, cls: Optional[str]) -> None:
        """`fn_expr` (a submit/map target) runs on `lane`."""
        if isinstance(fn_expr, ast.Lambda):
            for node in ast.walk(fn_expr.body):
                if isinstance(node, ast.Call):
                    self.mark_runs_on(node.func, lane, sf, qual, cls)
            return
        chain = attr_chain(fn_expr)
        if chain is None:
            return
        name = chain[-1]
        quals = self.by_name.get(name, [])
        if len(quals) == 1:
            self.runs_on.setdefault(quals[0], set()).add(lane)
        elif chain[0] == "self" and len(chain) == 2 and cls is not None:
            qual2 = f"{cls}.{name}"
            if qual2 in self.model.funcs:
                self.runs_on.setdefault(qual2, set()).add(lane)


def run(files: list[SourceFile], model: LockModel | None = None
        ) -> list[Finding]:
    model = model or LockModel(files)
    lanes = _Lanes(files, model)
    # blocking-submit lanes per function: (lane, file, line)
    blocking: dict[str, list] = {}

    by_rel = {s.rel: s for s in files}
    for qual in sorted(model.funcs):
        fi = model.funcs[qual]
        sf = by_rel.get(fi.file)
        if sf is None or sf.tree is None:
            continue
        fn_node = fi.node
        if fn_node is None:
            continue
        # local futures: var -> lane (from `v = E.submit(...)`)
        fut_lane: dict[str, str] = {}
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in ("submit",
                                                                 "map"):
                lane = lanes.lane_of(func.value, qual, fi.cls)
                if lane is not None and node.args:
                    lanes.mark_runs_on(node.args[0], lane, sf, qual, fi.cls)
                    if func.attr == "map":
                        # map() yields .result()s: blocking at the site
                        blocking.setdefault(qual, []).append(
                            (lane, fi.file, node.lineno))
            # fetch_ordered(items, fn, pool, ...): runs fn on pool's lane
            # and blocks the caller on its futures
            if (getattr(func, "id", None) == "fetch_ordered"
                    or getattr(func, "attr", None) == "fetch_ordered") \
                    and len(node.args) >= 3:
                lane = lanes.lane_of(node.args[2], qual, fi.cls)
                if lane is not None:
                    lanes.mark_runs_on(node.args[1], lane, sf, qual, fi.cls)
                    blocking.setdefault(qual, []).append(
                        (lane, fi.file, node.lineno))
            # E.submit(...).result() chained
            if isinstance(func, ast.Attribute) \
                    and func.attr in ("result", "exception") \
                    and isinstance(func.value, ast.Call) \
                    and isinstance(func.value.func, ast.Attribute) \
                    and func.value.func.attr == "submit":
                lane = lanes.lane_of(func.value.func.value, qual, fi.cls)
                if lane is not None:
                    blocking.setdefault(qual, []).append(
                        (lane, fi.file, node.lineno))
        # second sweep: assigned futures waited later in the same function.
        # `v = E.submit(...)` tracks the var; `c[i] = E.submit(...)` /
        # `c.append(E.submit(...))` marks the whole function as holding
        # lane futures in a container — any later bare `.result()` on an
        # untracked name is then a wait on that lane (RSlice._read shape).
        container_lanes: set[str] = set()
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr == "submit":
                lane = lanes.lane_of(node.value.func.value, qual, fi.cls)
                if lane is None:
                    continue
                if isinstance(node.targets[0], ast.Name):
                    fut_lane[node.targets[0].id] = lane
                else:
                    container_lanes.add(lane)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "append" and node.args \
                    and isinstance(node.args[0], ast.Call) \
                    and isinstance(node.args[0].func, ast.Attribute) \
                    and node.args[0].func.attr == "submit":
                lane = lanes.lane_of(node.args[0].func.value, qual, fi.cls)
                if lane is not None:
                    container_lanes.add(lane)
        if fut_lane or container_lanes:
            for node in ast.walk(fn_node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("result", "exception") \
                        and isinstance(node.func.value, ast.Name):
                    name = node.func.value.id
                    hit_lanes = [fut_lane[name]] if name in fut_lane \
                        else sorted(container_lanes)
                    for lane in hit_lanes:
                        blocking.setdefault(qual, []).append(
                            (lane, fi.file, node.lineno))

    # close runs_on and blocking over resolved calls
    runs_on = dict(lanes.runs_on)
    changed = True
    while changed:
        changed = False
        for qual, fi in model.funcs.items():
            mine = runs_on.get(qual)
            if not mine:
                continue
            for callee in fi.callees:
                tgt = runs_on.setdefault(callee, set())
                if not mine <= tgt:
                    tgt.update(mine)
                    changed = True
    blocks_star: dict[str, list] = {q: list(v) for q, v in blocking.items()}
    changed = True
    while changed:
        changed = False
        for qual, fi in model.funcs.items():
            mine = blocks_star.setdefault(qual, [])
            have = {b[0] for b in mine}
            for callee in fi.callees:
                for lane, f, ln in blocks_star.get(callee, []):
                    if lane not in have:
                        mine.append((lane, f, ln))
                        have.add(lane)
                        changed = True

    findings: list[Finding] = []
    discovered: dict[tuple[str, str], tuple[str, int, str]] = {}
    for qual in sorted(runs_on):
        for src in sorted(runs_on[qual]):
            for lane, f, ln in blocks_star.get(qual, []):
                discovered.setdefault((src, lane), (f, ln, qual))
    for (a, b), (f, ln, qual) in sorted(discovered.items()):
        if a == b:
            findings.append(Finding(
                f, ln, "lane-graph",
                f"{qual} runs on lane {a!r} and submit-and-waits on its own "
                "lane: with every worker parked in waiters, nothing runs "
                "the waited-on work",
            ))
        elif (a, b) not in DECLARED_LANE_EDGES:
            findings.append(Finding(
                f, ln, "lane-graph",
                f"undeclared lane dependency {a} -> {b} (via {qual}): add "
                "it to DECLARED_LANE_EDGES in tools/analyze/passes/"
                "lane_graph.py after review, keeping the graph acyclic",
            ))
    # acyclicity of declared + discovered
    graph: dict[str, set[str]] = {}
    for a, b in set(discovered) | set(DECLARED_LANE_EDGES):
        if a != b:
            graph.setdefault(a, set()).add(b)
    cyc = _find_cycle(graph)
    if cyc:
        findings.append(Finding(
            "tools/analyze/passes/lane_graph.py", 0, "lane-graph",
            "lane graph has a cycle: " + " -> ".join(cyc) + " — a full "
            "lane can park every worker of the next lane behind it",
        ))
    return findings


def _find_cycle(graph: dict[str, set[str]]) -> Optional[list[str]]:
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in set(graph) | {b for v in graph.values()
                                             for b in v}}
    path: list[str] = []

    def dfs(n: str) -> Optional[list[str]]:
        color[n] = GRAY
        path.append(n)
        for m in sorted(graph.get(n, ())):
            if color[m] == GRAY:
                return path[path.index(m):] + [m]
            if color[m] == WHITE:
                got = dfs(m)
                if got:
                    return got
        path.pop()
        color[n] = BLACK
        return None

    for n in sorted(color):
        if color[n] == WHITE:
            got = dfs(n)
            if got:
                return got
    return None


PASS = Pass(
    name="lane-graph",
    rules=("lane-graph",),
    run=run,
    doc="qos lane submission graph stays acyclic; no worker blocks on "
        "its own lane; new edges must be declared",
)
