"""Architecture-seam lints migrated onto the shared framework (ISSUE 7
satellite): the three AST checks that grew ad hoc in tools/lint_metrics.py
across PRs 3/5/6, now running over the pre-parsed file list with
framework findings.  tools/lint_metrics.py remains a thin compatibility
shim over these.

* ``resilience-seam`` (PR 3): every ``create_storage`` consumer reaches
  the backend through the resilience wrapper (``resilient(...)`` or via
  ``CachedStore``/``build_store``).
* ``ingest-seam`` (PR 5): ``WSlice._upload_block`` submissions flow
  through the ingest stage when the store has one.
* ``qos-seam`` (PR 6): no bare ``ThreadPoolExecutor`` outside ``qos/``
  and the whitelisted resilience elastic pool.
* ``compress-seam`` (ISSUE 8): write-path compression in ``chunk/``
  routes through the batched compression plane — no bare
  ``compressor.compress`` calls, and ``_put_block`` must actually reach
  ``compress_plane.compress_one``.
* ``prefetch-seam`` (ISSUE 11): speculative warming routes through the
  ``Prefetcher`` at PREFETCH class — readahead planning is SUBMITTED,
  never invoked on the read thread, and readahead/warm-hint paths never
  load blocks or hit the object store at foreground class.
* ``wbatch-seam`` (ISSUE 13): vfs write-path mutations route through the
  write batcher's seam — no bare ``do_mknod``/``do_write_chunk``/
  ``do_setattr`` from ``vfs/``, the BaseMeta mutation ops must consult
  ``wbatch``, and the drain must reach the engine ``group_txn`` (a
  refactor that quietly drops any of these reverts every mutation to
  one transaction per op, which no functional test catches — results
  stay identical, only the round trips regress).
* ``tpu-shard-seam`` (ISSUE 20): device placement in ``chunk/`` routes
  through the sharding plane — no bare ``jax.jit``/``device_put``/
  ``pjit``, and the ingest shared pack must reach ``shard_packed`` and
  ``estimate_packed``.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ..core import (
    Finding,
    Pass,
    SourceFile,
    attr_chain,
    call_name,
    parent_map,
)

# pools allowed to exist OUTSIDE the unified scheduler (paths relative
# to the analysis root, i.e. the package dir):
#   - qos/ itself (the scheduler's own workers);
#   - object/resilient.py (the elastic abandonment pool: a hung attempt
#     must be abandonable, which a shared bounded worker set cannot do).
QOS_SEAM_WHITELIST = ("qos/", "object/resilient.py")


def _pkg_rel(sf: SourceFile) -> str:
    """Path relative to the analysis root (`rel` keeps the root's own
    directory name as its first segment — strip it so the whitelist and
    the object-layer skip work for any root, incl. test fixtures)."""
    return sf.rel.split("/", 1)[1] if "/" in sf.rel else sf.rel


def run_qos_seam(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        if sf.tree is None or "ThreadPoolExecutor" not in sf.text:
            continue
        rel = _pkg_rel(sf)
        if any(rel.startswith(w) or rel == w for w in QOS_SEAM_WHITELIST):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) == "ThreadPoolExecutor":
                findings.append(Finding(
                    sf.rel, node.lineno, "qos-seam",
                    "bare ThreadPoolExecutor outside qos/ — submit through "
                    "the unified scheduler "
                    "(qos.global_scheduler().executor(lane, cls))",
                ))
    return findings


def run_resilience_seam(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        if sf.tree is None or "create_storage" not in sf.text:
            continue
        if _pkg_rel(sf).split("/", 1)[0] == "object":
            continue  # the wrapper layer itself
        # AST-level on both sides: bare-store detection AND coverage must
        # be real CALLS — a docstring mentioning "CachedStore(" must not
        # satisfy the check
        called = {call_name(node) for node in ast.walk(sf.tree)
                  if isinstance(node, ast.Call)}
        if "create_storage" not in called:
            continue
        if not called & {"resilient", "CachedStore", "build_store"}:
            findings.append(Finding(
                sf.rel, 0, "resilience-seam",
                "create_storage() result never passes through the "
                "resilience wrapper (use resilient(...) or "
                "CachedStore/build_store)",
            ))
    return findings


def run_ingest_seam(files: list[SourceFile]) -> list[Finding]:
    sf = next((s for s in files
               if s.rel.endswith("chunk/cached_store.py")), None)
    if sf is None or sf.tree is None:
        # only the real package tree must contain the seam — fixture
        # trees (unit tests, --root) simply have nothing to check
        if any(s.rel.startswith("juicefs_tpu/") for s in files):
            return [Finding("juicefs_tpu/chunk/cached_store.py", 0,
                            "ingest-seam",
                            "chunk/cached_store.py not found or unparseable")]
        return []
    return check_ingest_seam(sf)


def check_ingest_seam(sf: SourceFile) -> list[Finding]:
    """Inside `WSlice._upload_block`, every `_put_or_stage` submission
    must sit under an `if` whose test references `ingest`, and the guard
    must actually route somewhere (an ingest.submit call) — a refactor
    reintroducing an unconditional direct upload silently disables
    elision, which no functional test catches on a low-dup workload."""
    fn = None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "WSlice":
            for item in node.body:
                if isinstance(item, ast.FunctionDef) \
                        and item.name == "_upload_block":
                    fn = item
    if fn is None:
        return [Finding(sf.rel, 0, "ingest-seam",
                        "WSlice._upload_block not found")]
    parents = parent_map(fn)

    def guarded_by_ingest(node) -> bool:
        cur = node
        while id(cur) in parents:
            cur = parents[id(cur)]
            if isinstance(cur, ast.If) and any(
                isinstance(n, (ast.Name, ast.Attribute))
                and (getattr(n, "id", None) == "ingest"
                     or getattr(n, "attr", None) == "ingest")
                for n in ast.walk(cur.test)
            ):
                return True
        return False

    findings = [
        Finding(sf.rel, node.lineno, "ingest-seam",
                "WSlice._upload_block submits _put_or_stage outside an "
                "`ingest` guard — block uploads must flow through the "
                "ingest stage when the store has one")
        for node in ast.walk(fn)
        if isinstance(node, ast.Attribute) and node.attr == "_put_or_stage"
        and not guarded_by_ingest(node)
    ]
    has_submit = any(
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "submit"
        and (getattr(node.func.value, "id", None) == "ingest"
             or getattr(node.func.value, "attr", None) == "ingest")
        for node in ast.walk(fn)
    )
    if not has_submit:
        findings.append(Finding(
            sf.rel, 0, "ingest-seam",
            "WSlice._upload_block never calls ingest.submit(...) — the "
            "inline-dedup seam is gone",
        ))
    return findings


def run_compress_seam(files: list[SourceFile]) -> list[Finding]:
    """Write-path compression must route through the batched plane
    (ISSUE 8): a bare ``compressor.compress`` in ``chunk/`` silently
    reverts to the serial in-worker encode, which no functional test
    catches (output is byte-identical — only the wall time regresses).
    The decompress side is exempt: reads stay on the compressor."""
    findings: list[Finding] = []
    store_sf = None
    saw_pkg = False
    for sf in files:
        saw_pkg = saw_pkg or sf.rel.startswith("juicefs_tpu/")
        rel = _pkg_rel(sf)
        if not rel.startswith("chunk/") or sf.tree is None:
            continue
        if rel == "chunk/cached_store.py":
            store_sf = sf
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "compress"):
                v = node.func.value
                holder = getattr(v, "attr", None) or getattr(v, "id", None)
                if holder == "compressor":
                    findings.append(Finding(
                        sf.rel, node.lineno, "compress-seam",
                        "bare compressor.compress on the write path — "
                        "route through the batched compression plane "
                        "(compress_plane.compress_one/compress_blocks)",
                    ))
    if store_sf is not None:
        has_plane = any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("compress_one", "compress_blocks")
            for node in ast.walk(store_sf.tree)
        )
        if not has_plane:
            findings.append(Finding(
                store_sf.rel, 0, "compress-seam",
                "chunk/cached_store.py never calls the compression plane "
                "(compress_plane.compress_one) — the batched-compress "
                "seam is gone",
            ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/chunk/cached_store.py", 0, "compress-seam",
            "chunk/cached_store.py not found or unparseable",
        ))
    return findings


def run_meta_cache_seam(files: list[SourceFile]) -> list[Finding]:
    """VFS attr reads must route through the meta cache layer (ISSUE 9):
    a bare ``do_getattr``/``do_lookup`` from vfs/ bypasses the lease
    cache AND the per-tenant throttle, silently reverting the hot stat
    path to one engine round trip per call — which no functional test
    catches (results are identical, only the round trips regress).  The
    cache layer itself must stay wired: BaseMeta.getattr/lookup consult
    ``lease`` or the whole layer is dead code."""
    findings: list[Finding] = []
    base_sf = None
    saw_pkg = False
    for sf in files:
        saw_pkg = saw_pkg or sf.rel.startswith("juicefs_tpu/")
        rel = _pkg_rel(sf)
        if rel == "meta/base.py":
            base_sf = sf
        if not rel.startswith("vfs/") or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("do_getattr", "do_lookup")):
                findings.append(Finding(
                    sf.rel, node.lineno, "meta-cache-seam",
                    f"bare {node.func.attr} from vfs/ bypasses the meta "
                    "lease cache and the per-tenant throttle — call "
                    "meta.getattr/meta.lookup",
                ))
    if base_sf is not None and base_sf.tree is not None:
        for fn_name in ("getattr", "lookup"):
            fn = None
            for node in ast.walk(base_sf.tree):
                if isinstance(node, ast.ClassDef) and node.name == "BaseMeta":
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef) \
                                and item.name == fn_name:
                            fn = item
            if fn is None or not any(
                isinstance(n, ast.Attribute) and n.attr == "lease"
                for n in ast.walk(fn)
            ):
                findings.append(Finding(
                    base_sf.rel, fn.lineno if fn else 0, "meta-cache-seam",
                    f"BaseMeta.{fn_name} never consults the lease cache — "
                    "the meta cache layer is disconnected",
                ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/meta/base.py", 0, "meta-cache-seam",
            "meta/base.py not found or unparseable",
        ))
    return findings


# the methods that make up the speculative read path: they run at
# PREFETCH class and must never be invoked synchronously by a read, nor
# load blocks themselves (the Prefetcher owns the actual I/O)
_SPECULATIVE_FNS = ("_readahead", "_warm_next_shard")
_FOREGROUND_LOADS = ("_load_block", "new_reader")


def _fn_defs(tree, names) -> list[ast.FunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name in names]


def run_prefetch_seam(files: list[SourceFile]) -> list[Finding]:
    """Speculative warming must route through the Prefetcher at PREFETCH
    class (ISSUE 11).  A refactor that inlines `_readahead` back onto the
    read thread, or loads blocks from a readahead/warm path, silently
    moves speculative meta walks and object GETs onto foreground reads —
    results stay identical, only the read-path latency contract breaks,
    which no functional test catches."""
    findings: list[Finding] = []
    reader_sf = store_sf = server_sf = None
    saw_pkg = False
    for sf in files:
        saw_pkg = saw_pkg or sf.rel.startswith("juicefs_tpu/")
        rel = _pkg_rel(sf)
        if rel == "vfs/reader.py":
            reader_sf = sf
        elif rel == "chunk/cached_store.py":
            store_sf = sf
        elif rel == "cache/server.py":
            server_sf = sf
    if reader_sf is not None and reader_sf.tree is not None:
        # 1. planning is submitted, never called: any direct CALL of a
        # speculative method runs the chunk-meta walk on the caller (the
        # foreground read thread) — passing the method reference to an
        # executor is an Attribute argument, not a Call, and stays legal
        for node in ast.walk(reader_sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SPECULATIVE_FNS):
                findings.append(Finding(
                    reader_sf.rel, node.lineno, "prefetch-seam",
                    f"{node.func.attr} invoked synchronously — readahead "
                    "planning must be SUBMITTED at PREFETCH class "
                    "(DataReader.ppool), never run on the read thread",
                ))
        # 2. speculative bodies only WARM (store.prefetch / fetcher
        # .fetch); loading blocks there would run object GETs at the
        # planner's own pace instead of the bounded sheddable queue
        warms = False
        for fn in _fn_defs(reader_sf.tree, _SPECULATIVE_FNS):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                holder = (getattr(node.func.value, "attr", None)
                          or getattr(node.func.value, "id", None))
                if attr in _FOREGROUND_LOADS \
                        or (attr == "get" and holder == "storage"):
                    findings.append(Finding(
                        reader_sf.rel, node.lineno, "prefetch-seam",
                        f"{fn.name} loads blocks ({holder or ''}"
                        f".{attr}) — speculative paths may only enqueue "
                        "on the prefetch stage (store.prefetch)",
                    ))
                if attr in ("prefetch", "fetch"):
                    warms = True
        if not warms:
            findings.append(Finding(
                reader_sf.rel, 0, "prefetch-seam",
                "no speculative path ever reaches store.prefetch/"
                "fetcher.fetch — the readahead seam is gone",
            ))
        # 3. the plan executor must exist at PREFETCH class
        if not any(isinstance(n, ast.Attribute) and n.attr == "PREFETCH"
                   for n in ast.walk(reader_sf.tree)):
            findings.append(Finding(
                reader_sf.rel, 0, "prefetch-seam",
                "vfs/reader.py never references IOClass.PREFETCH — "
                "readahead planning lost its class",
            ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/vfs/reader.py", 0, "prefetch-seam",
            "vfs/reader.py not found or unparseable",
        ))
    if store_sf is not None and store_sf.tree is not None:
        # CachedStore.prefetch is the enqueue-only entry point: it must
        # route through the Prefetcher, and never load inline
        for node in ast.walk(store_sf.tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name == "CachedStore"):
                continue
            for item in node.body:
                if not (isinstance(item, ast.FunctionDef)
                        and item.name == "prefetch"):
                    continue
                calls = [n for n in ast.walk(item)
                         if isinstance(n, ast.Call)
                         and isinstance(n.func, ast.Attribute)]
                findings.extend(
                    Finding(store_sf.rel, c.lineno, "prefetch-seam",
                            "CachedStore.prefetch loads inline "
                            f"({c.func.attr}) — it may only enqueue on "
                            "the Prefetcher")
                    for c in calls if c.func.attr in _FOREGROUND_LOADS
                    or (c.func.attr == "get"
                        and getattr(c.func.value, "attr", None) == "storage")
                )
                if not any(c.func.attr == "fetch" for c in calls):
                    findings.append(Finding(
                        store_sf.rel, item.lineno, "prefetch-seam",
                        "CachedStore.prefetch never reaches "
                        "Prefetcher.fetch — the warming seam is gone",
                    ))
    if server_sf is not None and server_sf.tree is not None:
        # peer warm hints enqueue on the local prefetch stage — serving
        # them with a foreground load would let peers spend this member's
        # foreground budget
        for fn in _fn_defs(server_sf.tree, ("_warm",)):
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _FOREGROUND_LOADS):
                    findings.append(Finding(
                        server_sf.rel, node.lineno, "prefetch-seam",
                        "peer warm hint loads inline — it must enqueue "
                        "through the Prefetcher (PREFETCH class)",
                    ))
    return findings


# write-path engine ops that must never be called bare from vfs/ — the
# BaseMeta public ops front them with the write batcher (ISSUE 13)
_WBATCH_BANNED = ("do_mknod", "do_write_chunk", "do_setattr")
# BaseMeta ops that must consult the batcher seam
_WBATCH_FRONTED = ("mknod", "write_chunk")


def run_wbatch_seam(files: list[SourceFile]) -> list[Finding]:
    """VFS write mutations must route through the write batcher seam
    (ISSUE 13): a bare ``do_mknod``/``do_write_chunk``/``do_setattr``
    from vfs/ bypasses the overlay AND the group commit, silently
    reverting the checkpoint write path to one engine transaction per
    mutation; the batcher itself must stay wired (BaseMeta's mutation
    ops consult ``wbatch``, the drain reaches ``group_txn``)."""
    findings: list[Finding] = []
    base_sf = wb_sf = None
    saw_pkg = False
    for sf in files:
        saw_pkg = saw_pkg or sf.rel.startswith("juicefs_tpu/")
        rel = _pkg_rel(sf)
        if rel == "meta/base.py":
            base_sf = sf
        elif rel == "meta/wbatch.py":
            wb_sf = sf
        if not rel.startswith("vfs/") or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _WBATCH_BANNED):
                findings.append(Finding(
                    sf.rel, node.lineno, "wbatch-seam",
                    f"bare {node.func.attr} from vfs/ bypasses the write "
                    "batcher (overlay + group commit) — call the BaseMeta "
                    "public op",
                ))
    if base_sf is not None and base_sf.tree is not None:
        for fn_name in _WBATCH_FRONTED:
            fn = None
            for node in ast.walk(base_sf.tree):
                if isinstance(node, ast.ClassDef) and node.name == "BaseMeta":
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef) \
                                and item.name == fn_name:
                            fn = item
            if fn is None or not any(
                isinstance(n, ast.Attribute) and n.attr == "wbatch"
                for n in ast.walk(fn)
            ):
                findings.append(Finding(
                    base_sf.rel, fn.lineno if fn else 0, "wbatch-seam",
                    f"BaseMeta.{fn_name} never consults the write batcher "
                    "— the checkpoint write plane is disconnected",
                ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/meta/base.py", 0, "wbatch-seam",
            "meta/base.py not found or unparseable",
        ))
    if wb_sf is not None and wb_sf.tree is not None:
        # the drain must commit through the engine's group transaction —
        # without it every "batched" op silently runs per-op
        if not any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "group_txn"
            for n in ast.walk(wb_sf.tree)
        ):
            findings.append(Finding(
                wb_sf.rel, 0, "wbatch-seam",
                "meta/wbatch.py never calls group_txn — the group-commit "
                "seam is gone (every drain would run one txn per op)",
            ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/meta/wbatch.py", 0, "wbatch-seam",
            "meta/wbatch.py not found or unparseable",
        ))
    return findings


# engine-txn entry points that must never be invoked from the consumer
# layers: every engine interaction from vfs//chunk/ goes through a
# BaseMeta public op so the ISSUE 14 fault guard (classified retries,
# breaker gate, degraded mode) fronts it
_META_TXN_CALLS = ("txn", "simple_txn", "group_txn")
_DO_OP_RE = re.compile(r"^do_[a-z_]+$")


def run_meta_resilience_seam(files: list[SourceFile]) -> list[Finding]:
    """No bare engine ``do_*``/txn invocation from vfs/ or chunk/ —
    bypassing the BaseMeta public ops bypasses the meta fault contract
    (ISSUE 14): no classified retries, no breaker gate, no degraded
    serving, so one engine hiccup becomes a raw exception on the FUSE
    request path again — which no functional test catches until the
    engine actually fails.  The contract itself must stay wired:
    ``configure_meta_retries`` reaches ``resilience.configure`` and the
    guard's call loop consults the breaker."""
    findings: list[Finding] = []
    base_sf = res_sf = None
    saw_pkg = False
    for sf in files:
        saw_pkg = saw_pkg or sf.rel.startswith("juicefs_tpu/")
        rel = _pkg_rel(sf)
        if rel == "meta/base.py":
            base_sf = sf
        elif rel == "meta/resilient.py":
            res_sf = sf
        if sf.tree is None or rel.split("/", 1)[0] not in ("vfs", "chunk"):
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if _DO_OP_RE.match(attr):
                findings.append(Finding(
                    sf.rel, node.lineno, "meta-resilience-seam",
                    f"bare engine {attr} from {rel.split('/', 1)[0]}/ "
                    "bypasses the meta fault contract (retries/breaker/"
                    "degraded mode) — call the BaseMeta public op",
                ))
            elif attr in _META_TXN_CALLS:
                findings.append(Finding(
                    sf.rel, node.lineno, "meta-resilience-seam",
                    f"bare engine {attr}() from {rel.split('/', 1)[0]}/ "
                    "bypasses the meta fault contract — engine "
                    "transactions belong behind BaseMeta public ops",
                ))
    if base_sf is not None and base_sf.tree is not None:
        fn = None
        for node in ast.walk(base_sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == "BaseMeta":
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) \
                            and item.name == "configure_meta_retries":
                        fn = item
        if fn is None or not any(
            isinstance(n, ast.Attribute) and n.attr == "resilience"
            for n in ast.walk(fn)
        ):
            findings.append(Finding(
                base_sf.rel, fn.lineno if fn else 0, "meta-resilience-seam",
                "BaseMeta.configure_meta_retries never reaches the "
                "resilience layer — the meta fault contract is "
                "disconnected",
            ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/meta/base.py", 0, "meta-resilience-seam",
            "meta/base.py not found or unparseable",
        ))
    if res_sf is not None and res_sf.tree is not None:
        # the guard's retry loop must consult the breaker — without the
        # gate every "guarded" op dials a dead engine anyway and the
        # degraded ladder never engages
        call_fn = None
        for node in ast.walk(res_sf.tree):
            if isinstance(node, ast.ClassDef) \
                    and node.name == "MetaResilience":
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) \
                            and item.name == "_call":
                        call_fn = item
        if call_fn is None or not any(
            isinstance(n, ast.Attribute) and n.attr in ("_gate", "breaker")
            for n in ast.walk(call_fn)
        ):
            findings.append(Finding(
                res_sf.rel, call_fn.lineno if call_fn else 0,
                "meta-resilience-seam",
                "MetaResilience._call never consults the breaker gate — "
                "the meta breaker is dead code",
            ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/meta/resilient.py", 0, "meta-resilience-seam",
            "meta/resilient.py not found or unparseable",
        ))
    return findings


# object data-path functions: whole-body buffering (`_body`) or
# whole-object reads (`read_file`) there silently revert the gateway to
# RAM-buffered serving — results stay byte-identical, only the memory
# bound and the streaming-reader engagement vanish, which no functional
# test catches
_GW_DATA_PATHS = {
    "gateway/s3.py": ("_get_object", "_put_object", "_upload_part"),
    "gateway/webdav.py": ("do_GET", "do_PUT", "do_COPY"),
}
# the streaming helpers each adapter must actually reach
# (_stream_to_temp is the s3 adapter's temp-key wrapper OVER stream_in:
# the body still streams, it just lands behind an atomic rename)
_GW_STREAM_CALLS = {"stream_in", "stream_out", "stream_body_in",
                    "stream_file_out", "_stream_to_temp"}
# the s3 handler dispatch methods that must pass the admission gate
_GW_DISPATCH = ("do_GET", "do_HEAD", "do_PUT", "do_POST", "do_DELETE")


def run_gateway_seam(files: list[SourceFile]) -> list[Finding]:
    """Gateway data paths stream and dispatch is admission/qos-tagged
    (ISSUE 15): object bodies must move through the serving-plane
    streaming helpers (no ``fs.read_file``, no ``_body()`` buffering in
    a data path), every s3 dispatch method must enter ``admitted`` (the
    gate that sheds overload and applies the tenant scope), and the
    serving plane itself must reach ``tenant_scope`` — a refactor that
    drops any of these quietly reverts the gateway to unbounded
    RAM-buffered, tenant-blind serving."""
    findings: list[Finding] = []
    s3_sf = serve_sf = None
    saw_pkg = False
    for sf in files:
        saw_pkg = saw_pkg or sf.rel.startswith("juicefs_tpu/")
        rel = _pkg_rel(sf)
        if rel == "gateway/s3.py":
            s3_sf = sf
        elif rel == "gateway/serve.py":
            serve_sf = sf
        if not rel.startswith("gateway/") or sf.tree is None:
            continue
        if rel == "gateway/serve.py":
            continue  # the helper layer itself
        data_fns = _GW_DATA_PATHS.get(rel, ())
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr == "read_file":
                findings.append(Finding(
                    sf.rel, node.lineno, "gateway-seam",
                    "fs.read_file in a gateway adapter buffers a whole "
                    "object in RAM — stream through the serving-plane "
                    "helpers (gateway/serve.py)",
                ))
        for fn in _fn_defs(sf.tree, data_fns):
            streams = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name == "_body":
                    findings.append(Finding(
                        sf.rel, node.lineno, "gateway-seam",
                        f"{fn.name} buffers the request body (_body) — "
                        "object data paths must stream "
                        "(serve.stream_body_in / plane.stream_in)",
                    ))
                if name in _GW_STREAM_CALLS or name == "copy_range":
                    streams = True
            if not streams:
                findings.append(Finding(
                    sf.rel, fn.lineno, "gateway-seam",
                    f"{fn.name} never reaches a streaming helper "
                    "(stream_in/stream_out/copy_range) — the gateway "
                    "data-path seam is gone",
                ))
    if s3_sf is not None and s3_sf.tree is not None:
        for fn in _fn_defs(s3_sf.tree, _GW_DISPATCH):
            if not any(isinstance(n, ast.Attribute) and n.attr == "admitted"
                       for n in ast.walk(fn)):
                findings.append(Finding(
                    s3_sf.rel, fn.lineno, "gateway-seam",
                    f"{fn.name} dispatches outside the admission gate "
                    "(plane.admitted) — overload would queue unboundedly "
                    "and the request would run tenant-blind",
                ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/gateway/s3.py", 0, "gateway-seam",
            "gateway/s3.py not found or unparseable",
        ))
    if serve_sf is not None and serve_sf.tree is not None:
        adm = next((f for f in _fn_defs(serve_sf.tree, ("admitted",))), None)
        if adm is None or not any(
            isinstance(n, ast.Name) and n.id == "tenant_scope"
            for n in ast.walk(adm)
        ):
            findings.append(Finding(
                serve_sf.rel, adm.lineno if adm else 0, "gateway-seam",
                "ServingPlane.admitted never applies tenant_scope — "
                "admitted requests would run tenant-blind on the qos "
                "lanes",
            ))
    elif saw_pkg:
        findings.append(Finding(
            "juicefs_tpu/gateway/serve.py", 0, "gateway-seam",
            "gateway/serve.py not found or unparseable",
        ))
    return findings


# device entrypoints chunk/ must not call directly: placement and jit
# compilation belong to the sharding plane (tpu/sharding.py), which owns
# the mesh, the ragged-batch padding, and the degrade ladder. A bare
# device_put in chunk/ silently forks the shared-H2D contract (the batch
# transfers twice, unsharded) — results stay identical, only the
# transfer discipline vanishes, which no functional test catches.
_SHARD_DEVICE_CALLS = {"device_put", "pjit", "make_mesh"}


def run_tpu_shard_seam(files: list[SourceFile]) -> list[Finding]:
    """Hash/dedup/estimator consumers in chunk/ enter through the
    sharding plane (ISSUE 20): no bare ``jax.jit``/``jax.device_put``/
    ``pjit`` in chunk/, and the ingest worker's shared pack must reach
    the plane seam (``shard_packed``) and feed the estimator from it
    (``estimate_packed``)."""
    findings: list[Finding] = []
    ingest_sf = None
    saw_pkg = False
    for sf in files:
        saw_pkg = saw_pkg or sf.rel.startswith("juicefs_tpu/")
        rel = _pkg_rel(sf)
        if rel == "chunk/ingest.py":
            ingest_sf = sf
        if not rel.startswith("chunk/") or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            chain = attr_chain(node.func) or []
            if name in _SHARD_DEVICE_CALLS or (
                    name == "jit" and chain[:1] == ["jax"]):
                findings.append(Finding(
                    sf.rel, node.lineno, "tpu-shard-seam",
                    f"bare {name} in chunk/ — device placement and jit "
                    "belong to the sharding plane (route through "
                    "HashPipeline.shard_packed / tpu.sharding.get_plane)",
                ))
    if ingest_sf is None or ingest_sf.tree is None:
        if saw_pkg:
            findings.append(Finding(
                "juicefs_tpu/chunk/ingest.py", 0, "tpu-shard-seam",
                "chunk/ingest.py not found or unparseable"))
        return findings
    proc = next(iter(_fn_defs(ingest_sf.tree, ("_process",))), None)
    if proc is None:
        findings.append(Finding(
            ingest_sf.rel, 0, "tpu-shard-seam",
            "IngestPipeline._process not found — the shared-pack seam "
            "has no home"))
        return findings
    called = {call_name(n) for n in ast.walk(proc)
              if isinstance(n, ast.Call)}
    if "shard_packed" not in called:
        findings.append(Finding(
            ingest_sf.rel, proc.lineno, "tpu-shard-seam",
            "_process never reaches shard_packed — the shared pack "
            "bypasses the sharding plane (unsharded double transfer)"))
    if "estimate_packed" not in called:
        findings.append(Finding(
            ingest_sf.rel, proc.lineno, "tpu-shard-seam",
            "_process never feeds estimate_packed — the compress "
            "estimator lost the shared-H2D pack"))
    return findings


def run(files: list[SourceFile]) -> list[Finding]:
    return (run_qos_seam(files) + run_resilience_seam(files)
            + run_ingest_seam(files) + run_compress_seam(files)
            + run_meta_cache_seam(files) + run_prefetch_seam(files)
            + run_wbatch_seam(files) + run_meta_resilience_seam(files)
            + run_gateway_seam(files) + run_tpu_shard_seam(files))


PASS = Pass(
    name="seams",
    rules=("qos-seam", "resilience-seam", "ingest-seam", "compress-seam",
           "meta-cache-seam", "prefetch-seam", "wbatch-seam",
           "meta-resilience-seam", "gateway-seam", "tpu-shard-seam"),
    run=run,
    doc="architecture seams: scheduler-only pools, resilience-wrapped "
        "stores, ingest-guarded uploads, plane-routed compression, "
        "cache-routed vfs attr reads, prefetch-routed speculative reads, "
        "batcher-routed vfs write mutations, guard-routed engine calls, "
        "streaming/admitted gateway data paths, plane-routed device "
        "placement (no bare jit/device_put in chunk/)",
)
