"""Claim/rollback pairing lint (rule ``claim-rollback``).

PRs 8-10 grew a family of *claims*: a counter, set entry or reservation
is taken optimistically, work is attempted, and the claim must be
released on EVERY outcome — including the exception paths.  A leaked
claim is silent and cumulative: ``flush()`` waits forever on a
``_final_pending`` that never drains, a prefetch key stays "pending" and
is never re-fetched, a readahead reservation pins window bytes nothing
planned.  No functional test catches the leak until the exact failure
interleaving happens under load.

``CLAIM_REGISTRY`` names each acquire/release pair (like the lane
pass's DECLARED_LANE_EDGES, reviewed and updated with the code):

* between an acquire and the first matching release/handoff, every
  raise-capable call must be PROTECTED — inside a ``try`` whose handler
  or ``finally`` performs a release (a can-raise call between acquire
  and bare release is a finding);
* a function that acquires but can never reach a release or handoff is
  a finding outright;
* declared CONSUMERS (the other end of a queue handoff) must release in
  a ``finally`` — the claim crossed a thread, so only ``finally``
  discipline keeps it balanced;
* a registry entry that no longer matches any acquire site in its file
  is itself a finding (the registry must track refactors, not rot).

Calls to registered degrade-not-raise seams count as safe here — their
no-raise contract is enforced at their own definition by the degrade
pass (that composition is what lets ``FileReader.read`` hold the
``_ra_done`` reservation across ``submit_plan`` without a try/finally).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from ..core import Finding, Pass, SourceFile, attr_chain
from .degrade import SEAM_SAFE_NAMES
from .effects import is_safe_call


@dataclass(frozen=True)
class ClaimPair:
    file: str                       # pkg-relative path
    name: str                       # human-readable claim id
    acquire: tuple
    releases: tuple = ()
    handoffs: tuple = ()            # ownership transfer (queue put, ...)
    consumers: tuple = ()           # (func_name, (required releases...))
    funcs: tuple = ()               # restrict acquire scan to these defs


# matcher kinds:
#   ("aug+", attr)         self.attr += ...
#   ("aug-", attr)         self.attr -= ...
#   ("mcall", recv, m)     self.recv.m(...)
#   ("scall", m)           self.m(...)
#   ("maxassign", attr)    self.attr = max(self.attr, ...)
#   ("assign", attr)       self.attr = <expr>   (release/rollback form)
#   ("callm", m)           <anything>.m(...)
CLAIM_REGISTRY = (
    ClaimPair(
        file="chunk/ingest.py",
        name="ingest finalizer claim (_final_pending)",
        acquire=("aug+", "_final_pending"),
        releases=(("aug-", "_final_pending"),),
        handoffs=(("mcall", "_finalq", "put"),),
        consumers=(("_finalize_loop",
                    (("aug-", "_final_pending"),
                     ("scall", "_settle_inflight"))),),
    ),
    ClaimPair(
        file="chunk/ingest.py",
        name="in-flight-register overlay (_inflight_reg)",
        acquire=("mcall", "_inflight_reg", "setdefault"),
        releases=(("scall", "_settle_inflight"),
                  ("mcall", "_inflight_reg", "pop")),
        handoffs=(("mcall", "_finalq", "put"),),
    ),
    ClaimPair(
        file="chunk/prefetch.py",
        name="prefetch pending reservation (_pending)",
        acquire=("mcall", "_pending", "add"),
        releases=(("mcall", "_pending", "discard"),),
        consumers=(("_run_one", (("mcall", "_pending", "discard"),)),),
    ),
    ClaimPair(
        file="vfs/reader.py",
        name="readahead frontier reservation (_ra_done)",
        acquire=("maxassign", "_ra_done"),
        releases=(("assign", "_ra_done"),),
    ),
    ClaimPair(
        file="qos/limiter.py",
        name="bandwidth admission debt (gate must reach charge)",
        acquire=("callm", "gate"),
        releases=(("callm", "charge"),),
        funcs=("acquire",),
    ),
    # checkpoint write plane (ISSUE 13): a submit claims overlay/dirty
    # state for its queued op and hands it to the drain via the queue;
    # the drain consumer must release in a finally (a leaked claim pins
    # the pending-create overlay and the dependent-read barrier set
    # forever — every later read of that inode drains pointlessly, and a
    # pending dentry shadows the committed one)
    ClaimPair(
        file="meta/wbatch.py",
        name="wbatch overlay/dirty claim (submit -> drain release)",
        acquire=("scall", "_overlay_acquire"),
        releases=(("scall", "_overlay_release"),),
        handoffs=(("mcall", "_queue", "append"),),
        consumers=(("_drain_locked", (("scall", "_overlay_release"),)),),
    ),
)


def _pkg_rel(sf: SourceFile) -> str:
    return sf.rel.split("/", 1)[1] if "/" in sf.rel else sf.rel


def _matches(node, matcher) -> bool:
    kind = matcher[0]
    if kind in ("aug+", "aug-"):
        if not isinstance(node, ast.AugAssign):
            return False
        ok_op = isinstance(node.op, ast.Add) if kind == "aug+" \
            else isinstance(node.op, ast.Sub)
        chain = attr_chain(node.target)
        return ok_op and chain is not None and chain[0] == "self" \
            and chain[-1] == matcher[1]
    if kind == "mcall":
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            return False
        chain = attr_chain(node.func)
        return node.func.attr == matcher[2] and chain is not None \
            and len(chain) >= 3 and chain[0] == "self" \
            and chain[-2] == matcher[1]
    if kind == "scall":
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            return False
        chain = attr_chain(node.func)
        return chain == ["self", matcher[1]]
    if kind in ("maxassign", "assign"):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            return False
        chain = attr_chain(node.targets[0])
        if chain is None or chain[0] != "self" or chain[-1] != matcher[1]:
            return False
        is_max = (isinstance(node.value, ast.Call)
                  and getattr(node.value.func, "id", None) == "max")
        return is_max if kind == "maxassign" else not is_max
    if kind == "callm":
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == matcher[1])
    return False


def _any_match(node, matchers) -> bool:
    return any(_matches(node, m) for m in matchers)


@dataclass
class _FnScan:
    acquires: list = field(default_factory=list)    # lines
    terminators: list = field(default_factory=list)  # lines (release|handoff)
    risky: list = field(default_factory=list)  # (line, desc, protected)


def _scan_fn(fn, pair: ClaimPair) -> _FnScan:
    scan = _FnScan()
    term = tuple(pair.releases) + tuple(pair.handoffs)

    def walk(node, protected: bool, in_handler: bool = False):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return  # deferred code: its own contract
        if _matches(node, pair.acquire):
            scan.acquires.append(node.lineno)
        elif _any_match(node, term):
            # a release inside an except handler runs only on the
            # EXCEPTION path: it is protection, not the normal-flow
            # terminator the acquire's region scan looks for
            if not in_handler:
                scan.terminators.append(node.lineno)
        elif isinstance(node, ast.Call) and not is_safe_call(node):
            name = (getattr(node.func, "attr", None)
                    or getattr(node.func, "id", "?"))
            if name not in SEAM_SAFE_NAMES:
                scan.risky.append((node.lineno, f"{name}(...)", protected))
        if isinstance(node, ast.Try):
            releasing = _try_releases(node, pair)
            fin_rel = _region_releases(node.finalbody, pair)
            for st in node.body:
                walk(st, protected or releasing, in_handler)
            for h in node.handlers:
                for st in h.body:
                    walk(st, protected, True)
            # else-body exceptions BYPASS the handlers, so only a
            # finally-side release protects them
            for st in node.orelse:
                walk(st, protected or fin_rel, in_handler)
            for st in node.finalbody:
                walk(st, protected, in_handler)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, protected, in_handler)

    for st in fn.body:
        walk(st, False)
    return scan


def _try_releases(node: ast.Try, pair: ClaimPair) -> bool:
    """True when this try's handlers or finally perform a release —
    the protection that makes can-raise calls in its body claim-safe."""
    return any(_region_releases(r, pair)
               for r in [node.finalbody] + [h.body for h in node.handlers])


def _region_releases(region, pair: ClaimPair) -> bool:
    rel = tuple(pair.releases)
    for st in region:
        for sub in ast.walk(st):
            if _any_match(sub, rel):
                return True
    return False


def _fn_defs(sf: SourceFile):
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            yield node


def run(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    by_pkg = {_pkg_rel(sf): sf for sf in files}
    for pair in CLAIM_REGISTRY:
        sf = by_pkg.get(pair.file)
        if sf is None or sf.tree is None:
            continue  # fixture trees carry only the files they seed
        fns = [fn for fn in _fn_defs(sf)
               if not pair.funcs or fn.name in pair.funcs]
        matched = False
        for fn in fns:
            scan = _scan_fn(fn, pair)
            if not scan.acquires:
                continue
            matched = True
            for la in scan.acquires:
                after = [t for t in scan.terminators if t >= la]
                if not after:
                    findings.append(Finding(
                        sf.rel, la, "claim-rollback",
                        f"{pair.name}: acquired in {fn.name}() but no "
                        "release/handoff is reachable afterwards — the "
                        "claim leaks on every path"))
                    continue
                lr = min(after)
                for line, desc, protected in scan.risky:
                    if la < line < lr and not protected:
                        findings.append(Finding(
                            sf.rel, line, "claim-rollback",
                            f"{pair.name}: {desc} can raise between the "
                            f"acquire (line {la}) and the release "
                            f"(line {lr}) with no releasing "
                            "except/finally — the claim leaks on that "
                            "path"))
        if not matched:
            findings.append(Finding(
                sf.rel, 0, "claim-rollback",
                f"registry entry `{pair.name}` matches no acquire site "
                f"in {pair.file} — update CLAIM_REGISTRY with the "
                "refactor"))
            continue
        for cname, required in pair.consumers:
            cfn = next((f for f in _fn_defs(sf) if f.name == cname), None)
            if cfn is None:
                findings.append(Finding(
                    sf.rel, 0, "claim-rollback",
                    f"{pair.name}: declared consumer {cname}() not found "
                    "— update CLAIM_REGISTRY"))
                continue
            for req in required:
                if not _released_in_finally(cfn, req):
                    findings.append(Finding(
                        sf.rel, cfn.lineno, "claim-rollback",
                        f"{pair.name}: consumer {cname}() must release "
                        f"({req}) inside a finally — the claim crossed a "
                        "thread and only finally discipline balances it"))
    return findings


def _released_in_finally(fn, matcher) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            for st in node.finalbody:
                for sub in ast.walk(st):
                    if _matches(sub, matcher):
                        return True
    return False


PASS = Pass(
    name="claim-rollback",
    rules=("claim-rollback",),
    run=run,
    doc="registered claim/reservation pairs release on every exception "
        "path; queue-handoff consumers release in finally",
)
