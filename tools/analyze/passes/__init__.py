"""Analysis passes.  AST_PASSES share the pre-parsed file list (and a
single LockModel, built once by the runner); RUNTIME_PASSES import the
package and inspect live state (the metric registry)."""

from __future__ import annotations

from . import (blocking, claims, degrade, lane_graph, lock_order, metrics,
               seams, swallow, threads, txn_purity)

AST_PASSES = [
    lock_order.PASS,
    blocking.PASS,
    lane_graph.PASS,
    threads.PASS,
    seams.PASS,
    txn_purity.PASS,
    claims.PASS,
    degrade.PASS,
    swallow.PASS,
]
RUNTIME_PASSES = [metrics.PASS]
ALL_PASSES = AST_PASSES + RUNTIME_PASSES
