"""Shared effect-summary model (ISSUE 12): the substrate of the
effect/error-path passes, the way :class:`LockModel` is the substrate of
the concurrency passes.

For every function in the pre-parsed file list (reusing LockModel's
function walk, callee resolution and store tables) this builds a summary
of EXTERNALLY VISIBLE effects — the operations that make re-running a
piece of code observable from outside it:

* NON-IDEMPOTENT ``self``-state writes: ``self.X += ...`` /
  ``del self.X`` and mutating container calls ``self.X.append(...)``.
  Plain ``self.X = <value>`` (including subscript/attribute forms) is
  deliberately EXEMPT: a last-write-wins publish re-applies to the same
  end state on a rerun (the meta layer's TTL hint caches and insert-only
  ACL interning rely on this, and document their abort-safety); the
  runtime rerun twin (txnwatch) asserts the byte-identical-rerun part;
* global writes (``global`` declarations that are assigned);
* metric effects: ``.inc()/.dec()/.observe()`` (the registry idiom —
  ``_C.inc()``, ``_C.labels(...).inc()``);
* I/O and scheduling effects: object-store driver ops on store-like
  receivers (LockModel's tables) and executor/scheduler dispatch
  (``.submit/.map/fetch_ordered`` and prefetcher ``.fetch``).

Summaries are closed transitively over resolved same-class/module calls
(``impure_star``): extracting an effect into a helper must not launder
it — the exact `blocks_star` shape from the blocking pass.

What static resolution cannot see (effects behind dynamic dispatch,
mutation of aliased state through plain locals), the runtime rerun
harness (juicefs_tpu/utils/txnwatch.py) covers — the same division of
labor as LockModel vs lockwatch.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from ..core import SourceFile, attr_chain
from .locks import STOREISH_NAMES, LockModel

# metric registry mutators (".set" is deliberately absent: `tx.set` is
# the KV transaction write verb and a gauge .set is idempotent anyway)
METRIC_OPS = {"inc", "dec", "observe"}
LOG_OPS = {"debug", "info", "warning", "error", "exception", "critical",
           "log"}
# container/object methods that mutate their receiver non-idempotently
MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "add", "update", "remove",
    "discard", "clear", "pop", "popitem", "setdefault", "push",
}
# object-store driver verbs (network side effects; re-running PUTs or
# DELETEs double-applies them)
STORE_OPS = {"get", "put", "delete", "head", "copy", "list", "list_all",
             "upload_part"}
# executor/scheduler dispatch: a rerun would double-submit the work
SUBMIT_OPS = {"submit", "map", "fetch_ordered", "submit_plan"}

# calls that can be assumed not to raise / not to have external effects
# (consumed by the claim-rollback and degrade-not-raise passes): pure
# builtins plus the repo's well-known pure constructors/parsers
SAFE_NAME_CALLS = {
    "len", "str", "int", "float", "bytes", "bytearray", "bool", "list",
    "dict", "set", "tuple", "frozenset", "sorted", "min", "max", "sum",
    "abs", "divmod", "round", "isinstance", "issubclass", "getattr",
    "hasattr", "enumerate", "zip", "range", "repr", "id", "type", "print",
    "memoryview", "format",
    # repo-local pure helpers / cheap constructors; _settle_future is the
    # first-writer-wins future resolver (chunk/ingest.py) — it exists to
    # swallow the lost-race InvalidStateError, so it cannot raise
    "parse_block_key", "block_key", "Future", "Event", "OrderedDict",
    "_settle_future",
}
# attribute calls that cannot meaningfully raise: metric/log effects,
# container ops, future plumbing, lock-free bookkeeping
SAFE_ATTR_CALLS = (
    METRIC_OPS | LOG_OPS | MUTATING_METHODS
    | {"labels", "get", "items", "keys", "values", "add_done_callback",
       "set_result", "done", "cancelled", "startswith", "endswith",
       "split", "rsplit", "join", "encode", "decode", "strip", "lstrip",
       "rstrip", "to_bytes", "from_bytes", "qsize", "copy", "fromkeys",
       "move_to_end", "record", "kick",
       # no-raise primitive constructors reached as module attrs
       # (threading.Event() et al.)
       "Event", "Lock", "RLock", "Condition", "Semaphore"}
)


def is_safe_call(node: ast.Call) -> bool:
    """True for calls the error-path passes treat as no-raise."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in SAFE_NAME_CALLS
    if isinstance(fn, ast.Attribute):
        return fn.attr in SAFE_ATTR_CALLS
    return False


@dataclass
class Effect:
    kind: str    # self-write | self-mutate | global-write | metric | io
    desc: str
    line: int


@dataclass
class EffectInfo:
    """Per-function external-effect summary."""

    qual: str
    file: str
    effects: list = field(default_factory=list)   # [Effect]

    def first(self) -> Optional[Effect]:
        return self.effects[0] if self.effects else None


class EffectModel:
    """Effect summaries for every function LockModel resolved, plus the
    transitive closure ``impure_star`` over resolved callees."""

    def __init__(self, files: list[SourceFile],
                 lock_model: Optional[LockModel] = None):
        self.lock = lock_model if lock_model is not None else LockModel(files)
        self.files = files
        self.funcs: dict[str, EffectInfo] = {}
        for qual, fi in self.lock.funcs.items():
            if fi.node is not None:
                self.funcs[qual] = self._summarize(qual, fi)
        self._close()

    # -- per-function walk -------------------------------------------------
    def _summarize(self, qual: str, fi) -> EffectInfo:
        info = EffectInfo(qual, fi.file)
        fn = fi.node
        is_ctor = qual.endswith(".__init__")
        globals_declared: set[str] = set()
        for node in self._own_nodes(fn):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
            self._scan_node(node, fi, info, is_ctor, globals_declared)
        return info

    @staticmethod
    def _own_nodes(fn):
        """Walk `fn` skipping nested function/lambda bodies: deferred
        code's effects belong to its own summary (nested defs) or to the
        call-site analysis (lambdas), not to the enclosing frame."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _scan_node(self, node, fi, info: EffectInfo, is_ctor: bool,
                   globals_declared: set) -> None:
        # non-idempotent self.X writes (constructors are exempt: __init__
        # publishing attributes IS construction, and no txn closure is an
        # __init__; plain `self.X = v` is exempt everywhere — last-write-
        # wins publishes re-apply to the same end state on a rerun)
        if isinstance(node, ast.AugAssign):
            chain = attr_chain(node.target) or (
                attr_chain(node.target.value)
                if isinstance(node.target, ast.Subscript) else None)
            if chain and chain[0] == "self" and len(chain) >= 2 \
                    and not is_ctor:
                info.effects.append(Effect(
                    "self-write",
                    f"self.{'.'.join(chain[1:])} augmented (op=)",
                    node.lineno))
            elif isinstance(node.target, ast.Name) \
                    and node.target.id in globals_declared:
                info.effects.append(Effect(
                    "global-write", f"global {node.target.id} op= ...",
                    node.lineno))
        elif isinstance(node, ast.Assign):
            # writes to `global`-declared names stay flagged even in the
            # plain form: module state crosses every retry AND every txn
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in globals_declared:
                    info.effects.append(Effect(
                        "global-write", f"global {t.id} = ...",
                        node.lineno))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                chain = attr_chain(t) or (
                    attr_chain(t.value) if isinstance(t, ast.Subscript)
                    else None)
                if chain and chain[0] == "self" and not is_ctor:
                    info.effects.append(Effect(
                        "self-write", f"del self.{'.'.join(chain[1:])}",
                        node.lineno))
        elif isinstance(node, ast.Call):
            self._scan_call(node, fi, info, is_ctor)

    def _scan_call(self, node: ast.Call, fi, info: EffectInfo,
                   is_ctor: bool) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        attr = fn.attr
        chain = attr_chain(fn)
        recv = chain[:-1] if chain else None
        if attr in METRIC_OPS:
            # _C.inc() / _C.labels(...).inc(): the receiver is either a
            # name chain or a .labels(...) call — both are metric idioms
            holder = ""
            if recv:
                holder = ".".join(recv)
            elif isinstance(fn.value, ast.Call) \
                    and isinstance(fn.value.func, (ast.Attribute, ast.Name)):
                holder = (getattr(fn.value.func, "attr", None)
                          or getattr(fn.value.func, "id", "")) + "(...)"
            if holder and not holder.startswith("self."):
                info.effects.append(Effect(
                    "metric", f"{holder}.{attr}()", node.lineno))
            return
        if recv is None:
            return
        if attr in MUTATING_METHODS and recv[0] == "self" and len(recv) >= 2 \
                and not is_ctor:
            info.effects.append(Effect(
                "self-mutate", f"self.{'.'.join(recv[1:])}.{attr}(...)",
                node.lineno))
            return
        cls = fi.cls
        storeish = (
            recv[-1] in STOREISH_NAMES
            or (cls is not None and recv[0] == "self" and len(recv) == 2
                and recv[1] in self.lock.class_stores.get(cls, set()))
        )
        if attr in STORE_OPS and storeish:
            info.effects.append(Effect(
                "io", f"object-store {attr}() via {'.'.join(recv)}",
                node.lineno))
        elif attr in SUBMIT_OPS:
            info.effects.append(Effect(
                "io", f"{'.'.join(recv)}.{attr}(...) (scheduler dispatch)",
                node.lineno))
        elif attr == "fetch" and recv[-1] in ("prefetcher", "_prefetcher"):
            info.effects.append(Effect(
                "io", f"{'.'.join(recv)}.fetch(...) (prefetch enqueue)",
                node.lineno))

    # -- transitive closure ------------------------------------------------
    def _close(self) -> None:
        """impure_star: qual -> (kind, desc, file, line) of the first
        external effect reachable through resolved calls (fixpoint)."""
        self.impure_star: dict[str, tuple] = {}
        for qual, info in self.funcs.items():
            eff = info.first()
            if eff is not None:
                self.impure_star[qual] = (eff.kind, eff.desc, info.file,
                                          eff.line)
        changed = True
        while changed:
            changed = False
            for qual, fi in self.lock.funcs.items():
                if qual in self.impure_star:
                    continue
                for callee in fi.callees:
                    hit = self.impure_star.get(callee)
                    if hit is not None:
                        kind, desc, f, ln = hit
                        short = callee.rsplit("::", 1)[-1]
                        self.impure_star[qual] = (
                            kind, f"{short}() -> {desc}", f, ln)
                        changed = True
                        break

    def impurity_of(self, qual: str) -> Optional[tuple]:
        return self.impure_star.get(qual)
