"""Blocking-under-lock lint (rule ``blocking-under-lock``).

A thread that parks inside a ``with <lock>:`` scope pins every other
thread that needs the lock for the full park — the shape behind the
PR 6 pool-split deadlocks and most of this repo's historical stalls.
The blocking set lives in :mod:`locks` (``LockModel._check_blocking``)
and covers:

* ``Future.result()`` / ``Future.exception()`` (incl. chained
  ``pool.submit(...).result()`` — a scheduler wait under a lock);
* ``Queue.get()/put()`` without ``block=False``;
* ``Event.wait()``; ``time.sleep()``; ``Thread.join()``;
* object-store driver ops (``.get/.put/.delete/.head/.copy`` on a
  storage handle): network time under a lock starves the seam.

``Condition.wait()`` releases its own lock while parked, so it is only
flagged when OTHER locks stay held across the wait.  Calls into
same-class/module helpers that block are flagged at the call site
(transitive closure), since extracting the blocking op into a helper
must not launder it.  Intentional sites carry
``# analyze: allow(blocking-under-lock) -- reason``.
"""

from __future__ import annotations

from ..core import Finding, Pass, SourceFile
from .locks import LockModel


def run(files: list[SourceFile], model: LockModel | None = None
        ) -> list[Finding]:
    model = model or LockModel(files)
    blocks = model.blocks_star()
    findings: list[Finding] = []
    for qual in sorted(model.funcs):
        fi = model.funcs[qual]
        # direct blocking ops under a held lock
        for held, desc, line, released in fi.blocking:
            still = tuple(k for k in held if k != released)
            if not still:
                continue
            findings.append(Finding(
                fi.file, line, "blocking-under-lock",
                f"{desc} while holding {', '.join(still)} (in {qual}): "
                "the lock is pinned for the whole wait",
            ))
        # calls (while holding) into helpers that block somewhere
        for held, callee, line in fi.held_calls:
            hit = blocks.get(callee)
            if hit is None:
                continue
            desc, bfile, bline = hit
            short = callee.rsplit("::", 1)[-1]
            findings.append(Finding(
                fi.file, line, "blocking-under-lock",
                f"call to {short}() blocks ({desc} at {bfile}:{bline}) "
                f"while holding {', '.join(held)} (in {qual})",
            ))
    return findings


PASS = Pass(
    name="blocking-under-lock",
    rules=("blocking-under-lock",),
    run=run,
    doc="no blocking call (futures, queues, sleeps, driver I/O) under a lock",
)
