"""Advisory-seam error-path lint (rule ``degrade-not-raise``).

Some functions sit on seams whose written contract is *degrade, never
fail*: speculative prefetch bodies, cache-group peer fetch/warm, the
ingest submit path, peer warm-hint handlers.  An exception escaping one
of these either fails a foreground operation that the seam was supposed
to merely accelerate (reader readahead, dedup elision) or kills a daemon
worker/handler thread outright — and no functional test catches it,
because the happy path is byte-identical.  PRs 8-10 each grew one of
these seams; their exception paths are exactly where the next
deadlock-class bug hides.

``ADVISORY_SEAMS`` is the reviewed registry (like the lane pass's
DECLARED_LANE_EDGES): every listed function must route all risky work
through a broad ``except Exception`` handler that does not re-raise.
The checker walks the function body; any statement containing a
non-safe call (``effects.is_safe_call``) or a ``raise`` that is not
covered by such a handler is a finding.  Calls to OTHER registered
seams count as safe (their no-raise contract is enforced at their own
definition), as do resolved same-class/module helpers that are
themselves fully wrapped.  A registry entry whose function no longer
exists is itself a finding — the registry must track refactors, not rot.
"""

from __future__ import annotations

import ast

from ..core import Finding, Pass, SourceFile
from .effects import is_safe_call

# (pkg-relative file, class or None, function): the degrade-never-raise
# contract holders.  Reviewed; additions ride the PR that adds the seam.
ADVISORY_SEAMS = (
    ("cache/group.py", "CacheGroup", "fetch"),
    ("cache/group.py", "CacheGroup", "warm"),
    ("vfs/reader.py", "DataReader", "submit_plan"),
    ("vfs/reader.py", "DataReader", "submit_epoch_warm"),
    ("vfs/reader.py", "DataReader", "_warm_next_shard"),
    ("chunk/ingest.py", "IngestPipeline", "submit"),
    ("chunk/ingest.py", "IngestPipeline", "_passthrough"),
    ("chunk/prefetch.py", "Prefetcher", "fetch"),
    ("chunk/prefetch.py", "Prefetcher", "_run_one"),
    ("cache/server.py", "PeerBlockServer", "_warm"),
)

# seam functions callable-by-name from inside OTHER seams without being
# re-flagged (their no-raise contract is enforced at their definition).
# Generic verbs are excluded: `pool.submit` / `prefetcher.fetch` are NOT
# the registered seams of the same name and can absolutely raise.
SEAM_SAFE_NAMES = {fn for _f, _c, fn in ADVISORY_SEAMS} - {
    "submit", "fetch", "warm", "get", "put"}


def _pkg_rel(sf: SourceFile) -> str:
    return sf.rel.split("/", 1)[1] if "/" in sf.rel else sf.rel


def _find_fn(sf: SourceFile, cls: str, name: str):
    """Registry seams are always methods (the seam IS some class's
    contract surface), so resolution is class-scoped only."""
    if sf.tree is None:
        return None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and item.name == name:
                    return item
    return None


def _broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = [getattr(e, "id", getattr(e, "attr", None))
             for e in (t.elts if isinstance(t, ast.Tuple) else [t])]
    return any(n in ("Exception", "BaseException") for n in names)


def _protecting_try(node: ast.Try) -> bool:
    """A try that upholds the contract: some broad handler, and NO
    handler re-raises (a classified re-raise belongs above the seam)."""
    if not any(_broad_handler(h) for h in node.handlers):
        return False
    for h in node.handlers:
        for sub in ast.walk(h):
            if isinstance(sub, ast.Raise):
                return False
    return True


def _risky_calls(stmt) -> list:
    """(line, desc) for every raise-capable operation in `stmt`,
    ignoring nested function/lambda bodies (deferred code runs under its
    own contract)."""
    out = []
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Raise):
            out.append((node.lineno, "raise"))
        elif isinstance(node, ast.Call) and not is_safe_call(node):
            fn = node.func
            name = getattr(fn, "attr", None) or getattr(fn, "id", "?")
            if name not in SEAM_SAFE_NAMES:
                out.append((node.lineno, f"{name}(...)"))
        stack.extend(ast.iter_child_nodes(node))
    return out


def check_seam(sf: SourceFile, fn: ast.FunctionDef,
               label: str) -> list[Finding]:
    """Every risky statement must sit under a protecting try."""
    findings: list[Finding] = []

    def walk(stmts, covered: bool):
        for st in stmts:
            if isinstance(st, ast.Try):
                protects = covered or _protecting_try(st)
                walk(st.body, protects)
                for h in st.handlers:
                    walk(h.body, covered)
                # an `else:` body runs AFTER the try body completed —
                # its exceptions are NOT caught by the handlers above
                walk(st.orelse, covered)
                walk(st.finalbody, covered)
                continue
            if isinstance(st, (ast.If, ast.For, ast.While, ast.With)):
                for line, desc in _risky_calls_shallow(st):
                    if not covered:
                        findings.append(_finding(sf, line, label, desc))
                for body in _inner_bodies(st):
                    walk(body, covered)
                continue
            if not covered:
                for line, desc in _risky_calls(st):
                    findings.append(_finding(sf, line, label, desc))

    walk(fn.body, False)
    return findings


def _inner_bodies(st):
    if isinstance(st, (ast.If, ast.For, ast.While)):
        yield st.body
        yield st.orelse
    elif isinstance(st, ast.With):
        yield st.body


def _risky_calls_shallow(st) -> list:
    """Risky ops in the statement's own header expressions (an `if`
    test, a `for` iterator, a `with` context) — its nested bodies are
    walked separately so inner `try` blocks keep their effect."""
    headers = []
    if isinstance(st, ast.If) or isinstance(st, ast.While):
        headers = [st.test]
    elif isinstance(st, ast.For):
        headers = [st.iter]
    elif isinstance(st, ast.With):
        headers = [i.context_expr for i in st.items]
    out = []
    for h in headers:
        out.extend(_risky_calls(ast.Expr(value=h)))
    return out


def _finding(sf: SourceFile, line: int, label: str, desc: str) -> Finding:
    return Finding(
        sf.rel, line, "degrade-not-raise",
        f"{desc} can raise out of advisory seam {label} — the contract "
        "is degrade-never-fail: route it through a broad "
        "`except Exception` that logs/counts and falls back")


def run(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    by_pkg = {_pkg_rel(sf): sf for sf in files}
    saw_pkg = any(sf.rel.startswith("juicefs_tpu/") for sf in files)
    for file, cls, name in ADVISORY_SEAMS:
        sf = by_pkg.get(file)
        if sf is None:
            continue  # fixture trees check only the seams they define
        fn = _find_fn(sf, cls, name)
        if fn is None:
            if saw_pkg:
                findings.append(Finding(
                    sf.rel, 0, "degrade-not-raise",
                    f"registered advisory seam {cls}.{name} not "
                    "found — update ADVISORY_SEAMS with the refactor"))
            continue
        label = f"{cls}.{name}"
        findings.extend(check_seam(sf, fn, label))
    return findings


PASS = Pass(
    name="degrade-not-raise",
    rules=("degrade-not-raise",),
    run=run,
    doc="registered advisory seams (prefetch bodies, cache-group "
        "fetch/warm, ingest submit, warm-hint handlers) never let "
        "exceptions escape",
)
