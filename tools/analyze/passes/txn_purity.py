"""Transaction rerun-purity lint (rule ``txn-purity``).

Closures handed to the meta transaction seams rerun under optimistic
conflict retry (``meta/redis_kv.py`` txn retries=50, ditto
``tkv_client.py``; sqlite BUSY backoff reruns them too), so ANY effect a
closure applies outside its transaction object double-applies on retry:
a counter bump counts twice, an appended list grows twice, a submitted
upload runs twice, a ``self`` field ends up holding a discarded
attempt's value.  No functional test catches these — conflicts are rare
until the exact production contention the ROADMAP is building toward.

The rule: a closure that flows into ``txn/simple_txn/_txn/_rtxn/_etxn/
_txn_notify`` (lambda argument, local ``def``, ``self.method`` refe-
rence or module function) may only touch its transaction handle and its
own locals:

* no writes to ``self`` state and no mutating calls on it — transitively
  through resolved same-class/module helpers (EffectModel.impure_star:
  extracting the effect into a helper must not launder it);
* no NON-IDEMPOTENT mutation of captured (enclosing-scope) names:
  ``nonlocal`` writes, augmented assigns, ``captured.append(...)``,
  ``del captured[...]``.  Plain last-write-wins assigns
  (``captured.attr = v``, ``captured[k] = v``, ``self.X = v``) are
  exempt — a rerun re-applies them to the same end state, which the
  runtime twin verifies byte-for-byte;
* no metric increments, object-store calls or scheduler dispatch.

The one blessed idiom is RESET-FIRST accumulation: a closure whose
FIRST statements clear a captured container (``del msgs[:]`` /
``msgs.clear()``) may refill it — each rerun starts from empty, which is
exactly how ``_txn_notify`` keeps post-commit notifications exactly-once
(meta/kv.py).  The runtime twin (utils/txnwatch.py, JUICEFS_TXN_RERUN=1)
covers what this walk cannot see: aliased state reached through plain
locals, dynamic dispatch, nondeterminism.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Pass, SourceFile, attr_chain
from .effects import MUTATING_METHODS, EffectModel
from .locks import LockModel

TXN_SINKS = {"txn", "simple_txn", "_txn", "_rtxn", "_etxn", "_txn_notify"}

_KIND_MSG = {
    "self-write": "writes self state",
    "self-mutate": "mutates self state",
    "global-write": "writes a module global",
    "metric": "bumps a metric",
    "io": "performs I/O or scheduler dispatch",
}


def _assigned_names(fn) -> set[str]:
    """Names BOUND in `fn`'s own frame (params + plain assignments +
    for/with/walrus/comprehension targets).  AugAssign targets are
    deliberately excluded: `x += 1` on a name never plainly assigned is
    a captured-state augment, not a local."""
    out: set[str] = set()
    a = fn.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        out.add(arg.arg)
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    if isinstance(fn, ast.Lambda):
        return out
    for node in EffectModel._own_nodes(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                out.update(_target_names(t))
        elif isinstance(node, (ast.AnnAssign, ast.For, ast.AsyncFor)):
            out.update(_target_names(node.target))
        elif isinstance(node, ast.NamedExpr):
            out.update(_target_names(node.target))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    out.update(_target_names(item.optional_vars))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                out.update(_target_names(gen.target))
    return out


def _target_names(t) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(t):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


def _reset_first(fn) -> set[str]:
    """Captured names the closure clears up front (the blessed
    reset-first accumulator idiom): `del X[:]`, `X.clear()`,
    `X[:] = []` as a LEADING statement."""
    out: set[str] = set()
    body = getattr(fn, "body", None)
    if not isinstance(body, list):
        return out
    for st in body:
        if isinstance(st, ast.Delete) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Subscript) \
                and isinstance(st.targets[0].value, ast.Name):
            out.add(st.targets[0].value.id)
        elif isinstance(st, ast.Expr) and isinstance(st.value, ast.Call) \
                and isinstance(st.value.func, ast.Attribute) \
                and st.value.func.attr == "clear" \
                and isinstance(st.value.func.value, ast.Name):
            out.add(st.value.func.value.id)
        elif isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Subscript) \
                and isinstance(st.targets[0].value, ast.Name):
            out.add(st.targets[0].value.id)
        else:
            break
    return out


class _ClosureChecker:
    def __init__(self, model: EffectModel, sf: SourceFile, cls,
                 scope_qual: str):
        self.model = model
        self.sf = sf
        self.cls = cls
        self.scope = scope_qual
        self.findings: list[Finding] = []

    def _emit(self, line: int, what: str) -> None:
        self.findings.append(Finding(
            self.sf.rel, line, "txn-purity",
            f"txn closure {what} — closures rerun under conflict retry; "
            "move the effect after commit (or reset-first for "
            "accumulators)"))

    def check(self, fn, qual: Optional[str]) -> list[Finding]:
        """fn: the Lambda/FunctionDef AST; qual: its EffectModel name
        when it has one (nested defs, methods, module functions)."""
        local = _assigned_names(fn)
        nonlocals: set[str] = set()
        if not isinstance(fn, ast.Lambda):
            for node in EffectModel._own_nodes(fn):
                if isinstance(node, ast.Nonlocal):
                    nonlocals.update(node.names)
        local -= nonlocals
        exempt = _reset_first(fn)

        # 1. the closure's own summarized effects (self state, metrics,
        # I/O) — EffectModel already walked named closures; lambdas are
        # walked here
        if qual is not None and qual in self.model.funcs:
            for eff in self.model.funcs[qual].effects:
                self._emit(eff.line,
                           f"{_KIND_MSG[eff.kind]} ({eff.desc})")

        body_nodes = list(EffectModel._own_nodes(fn))
        if isinstance(fn, ast.Lambda):
            body_nodes = list(ast.walk(fn.body))

        for node in body_nodes:
            self._check_captured(node, local, nonlocals, exempt)
            if isinstance(node, ast.Call):
                if qual is None:
                    self._lambda_call_effects(node)
                self._check_transitive(node)
        return self.findings

    # -- captured-state mutation ------------------------------------------
    def _check_captured(self, node, local: set, nonlocals: set,
                        exempt: set) -> None:
        if isinstance(node, ast.AugAssign):
            t = node.target
            if isinstance(t, ast.Name) and t.id not in local \
                    and t.id not in exempt:
                self._emit(node.lineno,
                           f"augments captured name `{t.id}`")
            elif isinstance(t, (ast.Attribute, ast.Subscript)):
                root = self._captured_root(t, local, exempt)
                if root:
                    self._emit(node.lineno,
                               f"augments captured object `{root}`")
        elif isinstance(node, ast.Assign):
            # plain assigns are last-write-wins (rerun-idempotent) —
            # only nonlocal rebinding is flagged, because its usual
            # shape is an accumulator (`total = total + n`) in disguise
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in nonlocals:
                    self._emit(node.lineno,
                               f"assigns nonlocal `{t.id}`")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    root = self._captured_root(t, local, exempt)
                    if root:
                        self._emit(node.lineno,
                                   f"deletes from captured object `{root}`")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATING_METHODS \
                and isinstance(node.func.value, ast.Name):
            name = node.func.value.id
            if name not in local and name not in exempt:
                self._emit(node.lineno,
                           f"calls `{name}.{node.func.attr}(...)` on a "
                           "captured name")

    @staticmethod
    def _captured_root(t, local: set, exempt: set) -> Optional[str]:
        node = t
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        if isinstance(node, ast.Name) and node.id != "self" \
                and node.id not in local and node.id not in exempt:
            return node.id
        return None  # self.* handled by EffectModel; locals are fine

    # -- lambda direct effects (no EffectModel summary exists) -------------
    def _lambda_call_effects(self, node: ast.Call) -> None:
        from .effects import METRIC_OPS, STORE_OPS, SUBMIT_OPS
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        chain = attr_chain(fn)
        if fn.attr in METRIC_OPS:
            self._emit(node.lineno, f"bumps a metric (.{fn.attr}())")
        elif chain and fn.attr in STORE_OPS and (
                chain[-2] if len(chain) >= 2 else "") in ("storage",
                                                          "_storage"):
            self._emit(node.lineno, f"performs object-store {fn.attr}()")
        elif fn.attr in SUBMIT_OPS:
            self._emit(node.lineno, "dispatches scheduler work "
                                    f"(.{fn.attr}())")

    # -- transitive laundering through helpers -----------------------------
    def _check_transitive(self, node: ast.Call) -> None:
        callee = self.model.lock.resolve_callee(
            node, self.sf, self.cls, scope=self.scope)
        if callee is None:
            return
        hit = self.model.impurity_of(callee)
        if hit is None:
            return
        kind, desc, f, ln = hit
        short = callee.rsplit("::", 1)[-1]
        self.findings.append(Finding(
            self.sf.rel, node.lineno, "txn-purity",
            f"txn closure calls {short}() which {_KIND_MSG[kind]} "
            f"({desc} at {f}:{ln}) — rerun-unsafe through helpers"))


def run(files: list[SourceFile], model: LockModel | None = None,
        effects: EffectModel | None = None) -> list[Finding]:
    effects = effects or EffectModel(files, model)
    lock = effects.lock
    findings: list[Finding] = []
    by_file = {sf.rel: sf for sf in files}
    seen: set[tuple] = set()
    for qual in sorted(lock.funcs):
        fi = lock.funcs[qual]
        if fi.node is None:
            continue
        sf = by_file.get(fi.file)
        if sf is None:
            continue
        for node in EffectModel._own_nodes(fi.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in TXN_SINKS):
                continue
            closure = _closure_arg(node)
            if closure is None:
                continue
            fn_ast, cqual = _resolve_closure(closure, qual, fi, sf, lock)
            if fn_ast is None:
                continue
            key = (sf.rel, getattr(fn_ast, "lineno", node.lineno), cqual)
            if key in seen:
                continue  # one closure, one analysis (many sink sites)
            seen.add(key)
            checker = _ClosureChecker(effects, sf, fi.cls,
                                      cqual or qual)
            findings.extend(checker.check(fn_ast, cqual))
    return findings


def _closure_arg(call: ast.Call):
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "fn":
            return kw.value
    return None


def _resolve_closure(arg, qual: str, fi, sf: SourceFile, lock: LockModel):
    """(ast, effect-model qual or None) for the closure expression, or
    (None, None) when it cannot be resolved (params, foreign refs)."""
    if isinstance(arg, ast.Lambda):
        return arg, None
    if isinstance(arg, ast.Name):
        for cand in (f"{qual}.<{arg.id}>", f"{sf.rel}::{arg.id}"):
            target = lock.funcs.get(cand)
            if target is not None and target.node is not None:
                return target.node, cand
        return None, None
    if isinstance(arg, ast.Attribute):
        chain = attr_chain(arg)
        if chain and chain[0] == "self" and len(chain) == 2 \
                and fi.cls is not None:
            target = lock.funcs.get(f"{fi.cls}.{chain[1]}")
            if target is not None and target.node is not None:
                return target.node, f"{fi.cls}.{chain[1]}"
    return None, None


PASS = Pass(
    name="txn-purity",
    rules=("txn-purity",),
    run=run,
    doc="closures passed to txn/simple_txn rerun under conflict retry: "
        "no self/captured-state writes, metrics, I/O or dispatch — "
        "transitively through helpers",
)
