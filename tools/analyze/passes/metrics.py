"""Metric-registry lint as a framework pass (rule ``metric-registry``).

The one RUNTIME pass: it imports the metric-registering modules and
walks the live registry (naming/help/conflict hygiene plus the pinned
per-subsystem series sets from PRs 4/5/6).  It takes no source files and
emits registry-level findings (no file:line — these are fixed, never
suppressed).  Skipped when the runner is asked for AST-only analysis
(fixture trees, unit tests).
"""

from __future__ import annotations

from ..core import Finding, Pass, SourceFile

# pinned per-subsystem series (ISSUE 4/5/6 contracts): tests and the
# BENCHMARKS tables counter-assert these — a rename must fail CI, not
# silently zero a dashboard
CACHE_GROUP_PREFIX = "juicefs_cache_group_"
CACHE_GROUP_EXPECTED = {
    "juicefs_cache_group_peer_hits",
    "juicefs_cache_group_peer_misses",
    "juicefs_cache_group_peer_errors",
    "juicefs_cache_group_ring_size",
    "juicefs_cache_group_peer_get_seconds",
    "juicefs_cache_group_served",
    "juicefs_cache_group_served_bytes",
    "juicefs_cache_group_serve_misses",
    # ring-aware warm placement (ISSUE 11): hints sent / accepted
    "juicefs_cache_group_warm_hints",
    "juicefs_cache_group_warm_requests",
}
PREFETCH_PREFIX = "juicefs_prefetch_"
PREFETCH_EXPECTED = {
    # speculative-warming effectiveness (chunk/prefetch.py); used/issued
    # is the readahead window feedback signal (ISSUE 11)
    "juicefs_prefetch_issued",
    "juicefs_prefetch_duplicates",
    "juicefs_prefetch_dropped",
    "juicefs_prefetch_used",
    "juicefs_prefetch_warmed",
}
READAHEAD_PREFIX = "juicefs_readahead_"
READAHEAD_EXPECTED = {
    # epoch-streaming read path (ISSUE 11, vfs/reader.py)
    "juicefs_readahead_plans",
    "juicefs_readahead_plan_shed",
    "juicefs_readahead_streaming",
    "juicefs_readahead_epoch_warms",
    "juicefs_readahead_window_bytes",
    "juicefs_readahead_streaming_handles",
}
INGEST_PREFIX = "juicefs_ingest_"
INGEST_EXPECTED = {
    "juicefs_ingest_blocks",
    "juicefs_ingest_bytes",
    "juicefs_ingest_put_elided",
    "juicefs_ingest_put_elided_bytes",
    "juicefs_ingest_uploaded",
    "juicefs_ingest_passthrough",
    "juicefs_ingest_race_collapsed",
    "juicefs_ingest_errors",
    "juicefs_ingest_queue_blocks",
    # adaptive elision bypass (ISSUE 8, chunk/bypass.py)
    "juicefs_ingest_bypass",
    "juicefs_ingest_bypass_probes",
}
COMPRESS_PREFIX = "juicefs_compress_"
COMPRESS_EXPECTED = {
    # batched compression plane (ISSUE 8, tpu/compress_batch.py)
    "juicefs_compress_batch_blocks",
    "juicefs_compress_bytes_in",
    "juicefs_compress_bytes_out",
    "juicefs_compress_ratio",
    "juicefs_compress_degraded",
}
QOS_PREFIX = "juicefs_qos_"
QOS_EXPECTED = {
    "juicefs_qos_submitted",
    "juicefs_qos_completed",
    "juicefs_qos_shed",
    "juicefs_qos_wait_seconds",
    "juicefs_qos_queue_depth",
    "juicefs_qos_throttle_wait_seconds",
    "juicefs_qos_throttled_bytes",
}
META_CACHE_PREFIX = "juicefs_meta_cache_"
META_CACHE_EXPECTED = {
    # lease cache + replica routing (ISSUE 9, meta/cache.py + redis_kv.py)
    "juicefs_meta_cache_hits",
    "juicefs_meta_cache_misses",
    "juicefs_meta_cache_invalidates",
    "juicefs_meta_cache_lease_expired",
    "juicefs_meta_cache_replica_reads",
    "juicefs_meta_cache_replica_stale",
}
META_THROTTLE_PREFIX = "juicefs_meta_throttle_"
META_THROTTLE_EXPECTED = {
    # per-tenant meta-op token buckets (ISSUE 9, --meta-op-limit)
    "juicefs_meta_throttle_waits",
    "juicefs_meta_throttle_wait_seconds",
}
META_FAULT_PREFIX = "juicefs_meta_fault_"
META_FAULT_EXPECTED = {
    # meta-plane fault contract (ISSUE 14, meta/resilient.py): retry/
    # failure accounting per error class + hung-read abandonment
    "juicefs_meta_fault_retries",
    "juicefs_meta_fault_failures",
    "juicefs_meta_fault_abandoned",
}
META_BREAKER_PREFIX = "juicefs_meta_breaker_"
META_BREAKER_EXPECTED = {
    # per-engine-connection circuit breaker (ISSUE 14)
    "juicefs_meta_breaker_state",
    "juicefs_meta_breaker_trips",
    "juicefs_meta_breaker_resets",
}
META_STALE_PREFIX = "juicefs_meta_stale_"
META_STALE_EXPECTED = {
    # degraded-mode stale-lease serves, bounded by
    # --meta-degraded-max-stale (ISSUE 14, meta/cache.py)
    "juicefs_meta_stale_served",
}
GATEWAY_PREFIX = "juicefs_gateway_"
GATEWAY_EXPECTED = {
    # gateway serving plane (ISSUE 15, gateway/serve.py): admission,
    # tenancy and streaming-buffer accounting — the shed counter and the
    # stream-buffer gauge are acceptance counters (503-not-500 overload,
    # bounded per-request buffering)
    "juicefs_gateway_requests",
    "juicefs_gateway_shed",
    "juicefs_gateway_errors",
    "juicefs_gateway_auth_failures",
    "juicefs_gateway_bytes_in",
    "juicefs_gateway_bytes_out",
    "juicefs_gateway_request_seconds",
    "juicefs_gateway_inflight",
    "juicefs_gateway_stream_buffer_bytes",
}
TPU_SHARD_PREFIX = "juicefs_tpu_shard_"
TPU_SHARD_EXPECTED = {
    # multichip sharding plane (ISSUE 20, tpu/sharding.py): device/mesh
    # geometry, the ONE-sharded-transfer-per-batch counter the shared-pack
    # contract asserts, and the single-device-jit degrade counter
    "juicefs_tpu_shard_devices",
    "juicefs_tpu_shard_h2d_batches",
    "juicefs_tpu_shard_degraded",
}
META_WBATCH_PREFIX = "juicefs_meta_wbatch_"
META_WBATCH_EXPECTED = {
    # checkpoint write plane (ISSUE 13, meta/wbatch.py): the
    # batched/drained ratio is the group-commit amortization the
    # BENCH_r11 acceptance counter-asserts
    "juicefs_meta_wbatch_batched",
    "juicefs_meta_wbatch_drained",
    "juicefs_meta_wbatch_barrier_flushes",
    "juicefs_meta_wbatch_overlay_hits",
    "juicefs_meta_wbatch_passthrough",
}


def populate_registry() -> None:
    """Import the modules whose metrics register at import time, and the
    runtime registrations that are cheap to trigger."""
    import juicefs_tpu.cache.group          # noqa: F401  peer hit/miss/ring
    import juicefs_tpu.cache.server         # noqa: F401  peer served counters
    import juicefs_tpu.chunk.bypass         # noqa: F401  elision-bypass counters
    import juicefs_tpu.chunk.cached_store   # noqa: F401  staging gauges
    import juicefs_tpu.chunk.disk_cache     # noqa: F401  disk tier counters
    import juicefs_tpu.chunk.ingest         # noqa: F401  inline-dedup counters
    import juicefs_tpu.chunk.mem_cache      # noqa: F401  cache hit/miss/evict
    import juicefs_tpu.chunk.parallel       # noqa: F401  fetch_inflight gauge
    import juicefs_tpu.chunk.prefetch       # noqa: F401  prefetch effectiveness
    import juicefs_tpu.chunk.singleflight   # noqa: F401  dedup counters
    import juicefs_tpu.gateway.serve        # noqa: F401  serving-plane counters
    import juicefs_tpu.meta.cache           # noqa: F401  lease cache + throttle
    import juicefs_tpu.meta.resilient       # noqa: F401  meta fault contract
    import juicefs_tpu.meta.wbatch          # noqa: F401  write-batch plane
    import juicefs_tpu.metric.trace         # noqa: F401  stage rollup histogram
    import juicefs_tpu.object.metered       # noqa: F401  per-backend op meters
    import juicefs_tpu.object.resilient     # noqa: F401  retry/hedge/breaker
    import juicefs_tpu.object.sharding      # noqa: F401  shard routing counter
    import juicefs_tpu.qos.limiter          # noqa: F401  bandwidth throttling
    import juicefs_tpu.qos.scheduler        # noqa: F401  scheduler classes
    import juicefs_tpu.tpu.compress_batch   # noqa: F401  compression plane
    import juicefs_tpu.tpu.pipeline         # noqa: F401  batch metrics
    import juicefs_tpu.tpu.sharding         # noqa: F401  multichip plane
    import juicefs_tpu.vfs.reader           # noqa: F401  readahead/streaming
    from juicefs_tpu.metric import register_process_metrics

    register_process_metrics()


def _registry(registry=None):
    from juicefs_tpu.metric import global_registry

    if registry is None:
        populate_registry()
    return registry or global_registry()


def lint_registry(registry=None) -> list[str]:
    """Naming/help/conflict hygiene over the registry (legacy `lint()`
    contract: returns problem strings, empty = clean)."""
    reg = _registry(registry)
    problems: list[str] = []
    for m in reg.walk():
        if not m.name.startswith("juicefs_"):
            problems.append(f"{m.name}: metric name lacks the juicefs_ prefix")
        if not m.help.strip():
            problems.append(f"{m.name}: missing help string")
        if m.kind not in ("counter", "gauge", "histogram"):
            problems.append(f"{m.name}: unknown metric kind {m.kind!r}")
    problems.extend(reg.conflicts)
    return problems


def lint_pinned(prefix: str, expected: set[str], what: str,
                registry=None) -> list[str]:
    """Pin a subsystem's registry: every expected series exists, and no
    stray metric squats under the prefix unreviewed."""
    reg = _registry(registry)
    names = {m.name for m in reg.walk()}
    problems = [
        f"{name}: {what} metric missing from the registry"
        for name in sorted(expected - names)
    ]
    problems += [
        f"{name}: unreviewed metric under {prefix} (add it to "
        "the pinned set in tools/analyze/passes/metrics.py)"
        for name in sorted(n for n in names
                           if n.startswith(prefix) and n not in expected)
    ]
    return problems


def run(files: list[SourceFile]) -> list[Finding]:
    problems = (
        lint_registry()
        + lint_pinned(CACHE_GROUP_PREFIX, CACHE_GROUP_EXPECTED, "cache-group")
        + lint_pinned(INGEST_PREFIX, INGEST_EXPECTED, "ingest")
        + lint_pinned(QOS_PREFIX, QOS_EXPECTED, "qos")
        + lint_pinned(COMPRESS_PREFIX, COMPRESS_EXPECTED, "compress")
        + lint_pinned(META_CACHE_PREFIX, META_CACHE_EXPECTED, "meta-cache")
        + lint_pinned(META_THROTTLE_PREFIX, META_THROTTLE_EXPECTED,
                      "meta-throttle")
        + lint_pinned(META_FAULT_PREFIX, META_FAULT_EXPECTED, "meta-fault")
        + lint_pinned(META_BREAKER_PREFIX, META_BREAKER_EXPECTED,
                      "meta-breaker")
        + lint_pinned(META_STALE_PREFIX, META_STALE_EXPECTED, "meta-stale")
        + lint_pinned(META_WBATCH_PREFIX, META_WBATCH_EXPECTED,
                      "meta-wbatch")
        + lint_pinned(TPU_SHARD_PREFIX, TPU_SHARD_EXPECTED, "tpu-shard")
        + lint_pinned(PREFETCH_PREFIX, PREFETCH_EXPECTED, "prefetch")
        + lint_pinned(READAHEAD_PREFIX, READAHEAD_EXPECTED, "readahead")
        + lint_pinned(GATEWAY_PREFIX, GATEWAY_EXPECTED, "gateway")
    )
    return [Finding("", 0, "metric-registry", p) for p in problems]


PASS = Pass(
    name="metric-registry",
    rules=("metric-registry",),
    run=run,
    doc="metric naming/help/conflict hygiene + pinned per-subsystem series",
)
