"""Lock-order analysis (rule ``lock-order``): the classic ABBA deadlock,
caught statically.

From the shared :class:`LockModel` this pass builds the static lock
acquisition graph: an edge A -> B means some code path acquires B while
holding A — either a lexically nested ``with``, or a call made inside a
``with A:`` scope whose (transitive, same-class/module) callee acquires
B.  Two findings fall out:

* a CYCLE in the graph (A -> B somewhere, B -> A somewhere else): two
  threads walking the two paths concurrently deadlock.  Exactly the
  shape of the PR 1 mount deadlock and the pool-split deadlocks PR 6's
  lane graph replaced — now a CI failure instead of a lucky test.
* a NESTED re-acquisition of a non-reentrant lock (A -> A where A is a
  plain ``threading.Lock``): self-deadlock on the spot.

The graph is an over-approximation (paths are not proven concurrent);
a justified ``# analyze: allow(lock-order) -- reason`` on the reported
edge suppresses a vetted pair.
"""

from __future__ import annotations

from ..core import Finding, Pass, SourceFile
from .locks import LockModel


def _edges(model: LockModel) -> dict[tuple[str, str], tuple[str, int, str]]:
    """(held, acquired) -> (file, line, how) for every acquisition event;
    the FIRST site seen wins (deterministic: files and functions are
    walked in sorted order)."""
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    acq = model.acquires_star
    for qual in sorted(model.funcs):
        fi = model.funcs[qual]
        for held, key, line in fi.nested:
            for h in held:
                edges.setdefault((h, key), (fi.file, line, f"in {qual}"))
        for held, callee, line in fi.held_calls:
            for key, _site in acq.get(callee, {}).items():
                for h in held:
                    if h != key or model.kind_of(key) != "rlock":
                        edges.setdefault(
                            (h, key),
                            (fi.file, line,
                             f"in {qual} via {callee.rsplit('::', 1)[-1]}()"))
    return edges


def _cycles(edges) -> list[list[str]]:
    """Elementary cycles, deduped by node set (one finding per deadlock
    shape, not one per rotation).  Graphs here are tiny — a bounded DFS
    is plenty."""
    graph: dict[str, list[str]] = {}
    for a, b in edges:
        if a != b:   # self-edges are the separate self-deadlock finding
            graph.setdefault(a, []).append(b)
    for outs in graph.values():
        outs.sort()
    seen_sets: set[frozenset] = set()
    cycles: list[list[str]] = []

    def dfs(start: str, node: str, path: list[str]) -> None:
        for nxt in graph.get(node, ()):
            if nxt == start:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(path[:])
            elif nxt not in path and nxt > start and len(path) < 8:
                # only walk nodes > start: each cycle found exactly once,
                # from its smallest node
                path.append(nxt)
                dfs(start, nxt, path)
                path.pop()

    for n in sorted(graph):
        dfs(n, n, [n])
    return cycles


def run(files: list[SourceFile], model: LockModel | None = None
        ) -> list[Finding]:
    model = model or LockModel(files)
    edges = _edges(model)
    findings: list[Finding] = []
    # self-deadlock: nested acquisition of a non-reentrant lock
    for (a, b), (file, line, how) in sorted(edges.items()):
        if a == b and model.kind_of(a) == "lock":
            findings.append(Finding(
                file, line, "lock-order",
                f"nested acquisition of non-reentrant lock {a} ({how}): "
                "a thread already holding it deadlocks on the spot",
            ))
    for cyc in _cycles(edges):
        ring = cyc + [cyc[0]]
        sites = []
        for a, b in zip(ring, ring[1:]):
            f, ln, how = edges[(a, b)]
            sites.append(f"{a} -> {b} at {f}:{ln} ({how})")
        f0, l0, _ = edges[(ring[0], ring[1])]
        findings.append(Finding(
            f0, l0, "lock-order",
            "lock acquisition cycle (ABBA deadlock): " + "; ".join(sites),
        ))
    return findings


PASS = Pass(
    name="lock-order",
    rules=("lock-order",),
    run=run,
    doc="acyclic lock acquisition graph; no nested non-reentrant locks",
)
