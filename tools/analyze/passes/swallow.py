"""Silent-swallow lint (rule ``silent-swallow``).

An ``except Exception: pass`` in the data plane turns every future bug
in its try-body into an invisible one: the object layer's retry paths,
the chunk layer's cache/ingest machinery and the gateway's protocol
handlers all degrade *by contract*, but a degrade that is neither
counted, logged, nor classified is indistinguishable from working — the
operator has no signal, and the next refactor widens the try without
anyone noticing what it now hides.

The rule, scoped to ``object/``, ``chunk/`` and ``gateway/``: a handler
catching a BROAD type (bare ``except``, ``Exception``,
``BaseException``) must do at least one of

* re-raise (``raise`` / raise a classified error),
* log (``logger.debug/info/warning/error/exception``),
* count (a metric ``.inc()/.dec()/.observe()``),
* or USE the caught exception (``except ... as e`` with ``e``
  referenced — forwarding it into a future/fallback counts as
  classification).

Handlers for SPECIFIC exception types are exempt: naming the class IS
the classification (``except NotFoundError: pass`` on an idempotent
delete documents exactly what is being ignored).  The fix for a finding
is never to delete the handler — it is to narrow the type, or add the
one-line count/log that makes the degrade observable.
"""

from __future__ import annotations

import ast

from ..core import Finding, Pass, SourceFile
from .effects import LOG_OPS, METRIC_OPS

SCOPED_DIRS = ("object/", "chunk/", "gateway/")

BROAD = {"Exception", "BaseException"}


def _pkg_rel(sf: SourceFile) -> str:
    return sf.rel.split("/", 1)[1] if "/" in sf.rel else sf.rel


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(getattr(e, "id", getattr(e, "attr", None)) in BROAD
               for e in elts)


def _handled(handler: ast.ExceptHandler) -> bool:
    exc_name = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in LOG_OPS | METRIC_OPS:
                return True
        if exc_name and isinstance(node, ast.Name) \
                and node.id == exc_name and isinstance(node.ctx, ast.Load):
            return True
    return False


def run(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        rel = _pkg_rel(sf)
        if not rel.startswith(SCOPED_DIRS):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _handled(node):
                continue
            findings.append(Finding(
                sf.rel, node.lineno, "silent-swallow",
                "broad except swallows silently — count it, log it, "
                "narrow the exception type, or forward the error "
                "(`as e` + use)"))
    return findings


PASS = Pass(
    name="silent-swallow",
    rules=("silent-swallow",),
    run=run,
    doc="object//chunk//gateway/ broad except handlers must count, log, "
        "classify (narrow type) or forward the error",
)
