"""Daemon/shutdown lint (rules ``thread-daemon``, ``thread-shutdown``).

Two invariants the thread-leak guard in tests/conftest.py enforces only
dynamically (and only for non-daemon threads a test happens to leak):

* ``thread-daemon`` — every ``threading.Thread(...)`` must pass
  ``daemon=`` explicitly.  The default (inherit the creator's flag) is
  exactly how a helper meant to die with the process ends up non-daemon
  when constructed from a worker, and vice versa; the repo's convention
  (ARCHITECTURE "Concurrency model" table) is that daemon-ness is a
  per-thread design decision, written at the construction site.

* ``thread-shutdown`` — a thread or executor a class starts and KEEPS
  (``self.x = Thread(...)`` + ``self.x.start()``, or
  ``self.x = <sched>.executor(...)``) must be reachable from a
  ``close()/stop()/shutdown()/__exit__()`` path of that class: some
  teardown method must reference the attribute (join it, signal it,
  shut it down).  A kept-but-unstoppable worker is a leak the suite
  only notices when it is non-daemon AND a test leaks it.

Fire-and-forget local threads are fine when daemon=True (they die with
the process by design) or when the creating function joins them.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Pass, SourceFile, attr_chain, call_name

_TEARDOWN_NAMES = ("close", "stop", "shutdown", "__exit__", "unmount",
                   "disconnect", "terminate", "join", "cancel")


def _is_teardown(name: str) -> bool:
    """close/stop/shutdown and their variants (close_all, close_session,
    _stop, ...) count as teardown paths."""
    return name.lstrip("_").startswith(_TEARDOWN_NAMES) \
        or name in _TEARDOWN_NAMES


def _is_thread_ctor(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and call_name(node) == "Thread"


def _is_executor_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "executor")


def _daemon_kw(call: ast.Call) -> Optional[bool]:
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True   # explicit but dynamic: the decision is written
    return None


def run(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        _check_file(sf, findings)
    return findings


def _check_file(sf: SourceFile, findings: list[Finding]) -> None:
    # 1) every Thread(...) call carries an explicit daemon=
    for node in ast.walk(sf.tree):
        if _is_thread_ctor(node) and _daemon_kw(node) is None:
            findings.append(Finding(
                sf.rel, node.lineno, "thread-daemon",
                "threading.Thread(...) without an explicit daemon= — "
                "daemon-ness is inherited from the creating thread unless "
                "written down, which flips when the construction site "
                "moves onto a worker",
            ))

    # 2) kept threads/executors reachable from a teardown path
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        kept: dict[str, tuple[int, str, Optional[bool]]] = {}
        started_attrs: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                chain = attr_chain(node.targets[0])
                if chain and len(chain) == 2 and chain[0] == "self":
                    if _is_thread_ctor(node.value):
                        kept[chain[1]] = (node.lineno, "thread",
                                          _daemon_kw(node.value))
                    elif _is_executor_ctor(node.value):
                        kept[chain[1]] = (node.lineno, "executor", None)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "start":
                chain = attr_chain(node.func.value)
                if chain and len(chain) == 2 and chain[0] == "self":
                    started_attrs.add(chain[1])
        if not kept:
            continue
        teardown_refs: set[str] = set()
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _is_teardown(item.name):
                for node in ast.walk(item):
                    chain = attr_chain(node) if isinstance(
                        node, ast.Attribute) else None
                    if chain and len(chain) >= 2 and chain[0] == "self":
                        teardown_refs.add(chain[1])
                    # teardown may drain via a helper: one hop through
                    # self-calls keeps refactors honest without a closure
                    if isinstance(node, ast.Call):
                        cchain = attr_chain(node.func)
                        if cchain and len(cchain) == 2 \
                                and cchain[0] == "self":
                            for sub in cls.body:
                                if isinstance(sub, (ast.FunctionDef,
                                                    ast.AsyncFunctionDef)) \
                                        and sub.name == cchain[1]:
                                    for n2 in ast.walk(sub):
                                        c2 = attr_chain(n2) if isinstance(
                                            n2, ast.Attribute) else None
                                        if c2 and len(c2) >= 2 \
                                                and c2[0] == "self":
                                            teardown_refs.add(c2[1])
        for attr, (line, kind, _daemon) in sorted(kept.items()):
            if kind == "thread" and attr not in started_attrs:
                continue   # constructed but never started here
            if attr not in teardown_refs:
                findings.append(Finding(
                    sf.rel, line, "thread-shutdown",
                    f"{cls.name}.{attr} ({kind}) is started/kept but no "
                    f"{'/'.join(_TEARDOWN_NAMES[:3])} path of {cls.name} "
                    "references it — it cannot be torn down",
                ))

    # 3) fire-and-forget locals: non-daemon local threads must be joined
    #    in the same function (or stored on self, handled above)
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local_threads: dict[str, tuple[int, Optional[bool]]] = {}
        joined: set[str] = set()
        anon: list[tuple[int, Optional[bool]]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_thread_ctor(node.value):
                local_threads[node.targets[0].id] = (
                    node.lineno, _daemon_kw(node.value))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                if node.func.attr == "join" \
                        and isinstance(node.func.value, ast.Name):
                    joined.add(node.func.value.id)
                elif node.func.attr == "start" \
                        and _is_thread_ctor(node.func.value):
                    anon.append((node.lineno, _daemon_kw(node.func.value)))
        for name, (line, daemon) in sorted(local_threads.items()):
            if daemon is False and name not in joined:
                findings.append(Finding(
                    sf.rel, line, "thread-shutdown",
                    f"non-daemon local thread {name!r} is never joined in "
                    "its creating function and not kept on self — nothing "
                    "can stop it",
                ))
        for line, daemon in anon:
            if daemon is False:
                findings.append(Finding(
                    sf.rel, line, "thread-shutdown",
                    "anonymous non-daemon Thread(...).start(): no handle "
                    "exists to join or stop it",
                ))


PASS = Pass(
    name="threads",
    rules=("thread-daemon", "thread-shutdown"),
    run=run,
    doc="explicit daemon= on every Thread; kept threads/executors "
        "reachable from a close/stop/shutdown path",
)
