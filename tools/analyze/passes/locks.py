"""Shared lock/primitive inference for the concurrency passes.

Builds, from the pre-parsed file list, a repo-wide model of:

* lock-like attributes — ``self.X = threading.Lock()/RLock()/Condition()``
  (or ``utils.Cond()``) assigned anywhere in a class, plus module-level
  ``X = threading.Lock()`` — keyed ``Class.attr`` / ``module.py::name``;
* condition aliasing — ``self._cond = threading.Condition(self._lock)``
  makes acquiring ``_cond`` identical to acquiring ``_lock``;
* other primitives the blocking pass needs: Event / Queue / Thread /
  executor / store-like attributes;
* per-function summaries: which locks a function acquires and which
  blocking operations it performs, with the lock stack held at each
  event, closed transitively over same-class / same-module calls.

Resolution is deliberately conservative: ``with self._lock`` resolves via
the enclosing class; a bare ``with _lock`` via the module table; a
foreign chain (``obj.attr._lock``) resolves only when the terminal
attribute name is defined by exactly ONE class in the repo (unique-name
resolution) — ambiguous names stay unresolved rather than guessing.
What static resolution cannot see (locks reached through dynamic
dispatch), the runtime watchdog (juicefs_tpu/utils/lockwatch.py) covers.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from ..core import SourceFile, attr_chain, call_name

LOCK_FACTORIES = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Cond": "condition",   # juicefs_tpu.utils.Cond wraps a Condition
}
EVENT_FACTORIES = {"Event"}
QUEUE_FACTORIES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
STORE_FACTORIES = {"create_storage", "resilient", "gated", "shaped", "metered"}
# attribute names treated as object-store handles even without an inferred
# assignment (the driver seam: .get/.put/... on these blocks on the network)
STOREISH_NAMES = {"storage", "_storage"}
# receiver names treated as Events without an inferred assignment
EVENTISH_NAMES = {"done"}


def class_id(sf: SourceFile, cls_name: str) -> str:
    """File-scoped class identity: two files may both define a class X
    without their locks/methods merging into one analysis node."""
    return f"{sf.rel}::{cls_name}"


def _factory_kind(node: ast.AST, table) -> Optional[str]:
    """Kind when `node` is a call to one of the factory names (either
    `threading.Lock()` / `queue.Queue()` or a bare imported name)."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name is None:
        return None
    if isinstance(table, dict):
        return table.get(name)
    return name if name in table else None


@dataclass
class LockInfo:
    key: str            # "Class.attr" or "mod.py::name"
    kind: str           # lock | rlock | condition
    file: str
    line: int
    alias_of: Optional[str] = None   # Condition(self._lock) -> that lock


@dataclass
class FuncInfo:
    """Per-function concurrency summary."""

    qual: str          # "file.py::Class.method" or "file.py::func"
    file: str
    cls: Optional[str]            # file-scoped class id, or None
    node: Optional[ast.AST] = None   # the def's AST (lane pass re-walks it)
    # locks acquired lexically in this function: {key: first site line}
    acquires: dict = field(default_factory=dict)
    # resolved same-class/module callees
    callees: set = field(default_factory=set)
    # (held_keys_tuple, acquired_key, line): nested acquisition events
    nested: list = field(default_factory=list)
    # (held_keys_tuple, callee_qual, line): calls made while holding
    held_calls: list = field(default_factory=list)
    # blocking ops ANYWHERE in the function (held may be empty):
    # (held_keys_tuple, op_desc, line, released_key_or_None)
    blocking: list = field(default_factory=list)


class LockModel:
    def __init__(self, files: list[SourceFile]):
        self.files = files
        self.locks: dict[str, LockInfo] = {}
        self.class_locks: dict[str, dict[str, LockInfo]] = {}
        self.class_events: dict[str, set[str]] = {}
        self.class_queues: dict[str, set[str]] = {}
        self.class_threads: dict[str, set[str]] = {}
        self.class_stores: dict[str, set[str]] = {}
        self.module_locks: dict[str, dict[str, LockInfo]] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self._known: set[str] = set()  # all resolvable qualnames, pre-walk
        self._attr_owner: dict[str, set[str]] = {}  # lock attr -> class ids
        for sf in files:
            if sf.tree is not None:
                self._collect_defs(sf)
                for node in sf.tree.body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._known.add(f"{sf.rel}::{node.name}")
                    elif isinstance(node, ast.ClassDef):
                        cid = class_id(sf, node.name)
                        for item in node.body:
                            if isinstance(item, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef)):
                                self._known.add(f"{cid}.{item.name}")
        for sf in files:
            if sf.tree is not None:
                self._collect_funcs(sf)
        self._close_acquires()

    # -- definition collection --------------------------------------------
    def _collect_defs(self, sf: SourceFile) -> None:
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _factory_kind(node.value, LOCK_FACTORIES)
                if kind:
                    key = f"{sf.rel}::{node.targets[0].id}"
                    info = LockInfo(key, kind, sf.rel, node.lineno)
                    self.locks[key] = info
                    self.module_locks.setdefault(sf.rel, {})[
                        node.targets[0].id] = info
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cls = class_id(sf, node.name)
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                    continue
                chain = attr_chain(sub.targets[0])
                if chain is None or len(chain) != 2 or chain[0] != "self":
                    continue
                attr = chain[1]
                kind = _factory_kind(sub.value, LOCK_FACTORIES)
                if kind:
                    key = f"{cls}.{attr}"
                    alias = None
                    if kind == "condition" and isinstance(sub.value, ast.Call) \
                            and sub.value.args:
                        inner = attr_chain(sub.value.args[0])
                        if inner and len(inner) == 2 and inner[0] == "self":
                            alias = f"{cls}.{inner[1]}"
                    info = LockInfo(key, kind, sf.rel, sub.lineno, alias)
                    self.locks[key] = info
                    self.class_locks.setdefault(cls, {})[attr] = info
                    self._attr_owner.setdefault(attr, set()).add(cls)
                elif _factory_kind(sub.value, EVENT_FACTORIES):
                    self.class_events.setdefault(cls, set()).add(attr)
                elif _factory_kind(sub.value, QUEUE_FACTORIES):
                    self.class_queues.setdefault(cls, set()).add(attr)
                elif _factory_kind(sub.value, {"Thread"}):
                    self.class_threads.setdefault(cls, set()).add(attr)
                elif _factory_kind(sub.value, STORE_FACTORIES):
                    self.class_stores.setdefault(cls, set()).add(attr)

    # -- lock expression resolution ---------------------------------------
    def canonical(self, key: str) -> str:
        """Follow Condition-over-lock aliases to the underlying lock."""
        seen = set()
        info = self.locks.get(key)
        while info is not None and info.alias_of and key not in seen:
            seen.add(key)
            key = info.alias_of
            info = self.locks.get(key)
        return key

    def resolve_lock(self, expr: ast.AST, sf: SourceFile,
                     cls: Optional[str]) -> Optional[str]:
        """Lock key for an acquisition expression, or None if unknown."""
        chain = attr_chain(expr)
        if chain is None:
            return None
        if len(chain) == 1:
            info = self.module_locks.get(sf.rel, {}).get(chain[0])
            return self.canonical(info.key) if info is not None else None
        if chain[0] == "self" and len(chain) == 2 and cls is not None:
            info = self.class_locks.get(cls, {}).get(chain[1])
            if info is not None:
                return self.canonical(info.key)
        # foreign chain (`obj.x._lock`): unique-attribute-name resolution
        owners = self._attr_owner.get(chain[-1], set())
        if len(owners) == 1:
            return self.canonical(f"{next(iter(owners))}.{chain[-1]}")
        return None

    def kind_of(self, key: str) -> str:
        info = self.locks.get(key)
        return info.kind if info is not None else "lock"

    # -- function walk -----------------------------------------------------
    def _collect_funcs(self, sf: SourceFile) -> None:
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_fn(node, f"{sf.rel}::{node.name}", sf, None)
            elif isinstance(node, ast.ClassDef):
                cid = class_id(sf, node.name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._walk_fn(item, f"{cid}.{item.name}", sf, cid)

    def _walk_fn(self, fn, qual: str, sf: SourceFile, cls) -> FuncInfo:
        fi = FuncInfo(qual, sf.rel, cls, fn)
        self.funcs[qual] = fi
        self._walk_stmts(fn.body, sf, cls, fi, held=())
        return fi

    def resolve_callee(self, call: ast.Call, sf: SourceFile, cls,
                       scope: str = "") -> Optional[str]:
        chain = attr_chain(call.func)
        if chain is None:
            return None
        if len(chain) == 1:
            for qual in (f"{scope}.<{chain[0]}>", f"{sf.rel}::{chain[0]}"):
                if qual in self.funcs or qual in self._known:
                    return qual
            return None
        if chain[0] == "self" and len(chain) == 2 and cls is not None:
            qual = f"{cls}.{chain[1]}"
            return qual if qual in self._known or qual in self.funcs else None
        return None

    def _walk_stmts(self, stmts, sf, cls, fi, held) -> None:
        for st in stmts:
            self._walk_stmt(st, sf, cls, fi, held)

    def _walk_stmt(self, st: ast.stmt, sf, cls, fi: FuncInfo, held) -> None:
        if isinstance(st, ast.With):
            inner = held
            for item in st.items:
                key = self.resolve_lock(item.context_expr, sf, cls)
                if key is not None:
                    fi.acquires.setdefault(key, item.context_expr.lineno)
                    if inner:
                        fi.nested.append((inner, key,
                                          item.context_expr.lineno))
                    inner = inner + (key,)
                else:
                    self._scan_expr(item.context_expr, sf, cls, fi, held)
            self._walk_stmts(st.body, sf, cls, fi, inner)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body runs when CALLED, not here: summarize it
            # under a scoped name so call sites can resolve it, and do not
            # inherit the current lock stack into it
            self._walk_fn(st, f"{fi.qual}.<{st.name}>", sf, cls)
            return
        for _field, value in ast.iter_fields(st):
            if isinstance(value, ast.stmt):
                self._walk_stmt(value, sf, cls, fi, held)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._walk_stmt(v, sf, cls, fi, held)
                    elif isinstance(v, ast.expr):
                        self._scan_expr(v, sf, cls, fi, held)
            elif isinstance(value, ast.expr):
                self._scan_expr(value, sf, cls, fi, held)

    def _scan_expr(self, expr: ast.expr, sf, cls, fi: FuncInfo, held) -> None:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                # a lambda body runs when CALLED, not where it is written:
                # `cb(lambda: fut.result())` under a lock defers the wait
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            callee = self.resolve_callee(node, sf, cls, scope=fi.qual)
            if callee is not None:
                fi.callees.add(callee)
                if held:
                    fi.held_calls.append((held, callee, node.lineno))
            self._check_blocking(node, sf, cls, fi, held)

    # -- blocking-op detection (consumed by passes/blocking.py) ------------
    # The configurable blocking set: operations that park the calling
    # thread for unbounded/IO time.  Extend here, not in the pass.
    def _check_blocking(self, call: ast.Call, sf, cls, fi: FuncInfo,
                        held) -> None:
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in ("result",
                                                             "exception"):
            # any .result()/.exception() call is a future wait — covers
            # `fut.result()` AND chained `pool.submit(...).result()`
            fi.blocking.append((held, f"Future.{func.attr}()",
                                call.lineno, None))
            return
        chain = attr_chain(func)
        if chain is None:
            return
        tail, recv = chain[-1], chain[:-1]
        desc = released = None
        if chain in (["time", "sleep"], ["_time", "sleep"]):
            desc = "time.sleep()"
        elif tail == "wait" and recv:
            key = self.resolve_lock(call.func.value, sf, cls)
            if key is not None and (self.kind_of(key) == "condition"
                                    or key in held):
                # Condition.wait releases its own lock while blocked —
                # only the OTHER held locks make it a finding
                desc, released = "Condition.wait()", key
            elif (cls is not None and recv[0] == "self" and len(recv) == 2
                    and recv[1] in self.class_events.get(cls, set())) \
                    or recv[-1] in EVENTISH_NAMES \
                    or recv[-1].endswith("event"):
                desc = "Event.wait()"
        elif tail in ("get", "put") and recv:
            is_queue = (cls is not None and recv[0] == "self" and len(recv) == 2
                        and recv[1] in self.class_queues.get(cls, set()))
            is_store = (recv[-1] in STOREISH_NAMES
                        or (cls is not None and recv[0] == "self"
                            and len(recv) == 2
                            and recv[1] in self.class_stores.get(cls, set())))
            if is_queue and not _queue_nonblocking(call):
                desc = f"Queue.{tail}()"
            elif is_store:
                desc = f"object-store {tail}()"
        elif tail in ("delete", "head", "copy") and recv and (
                recv[-1] in STOREISH_NAMES
                or (cls is not None and recv[0] == "self" and len(recv) == 2
                    and recv[1] in self.class_stores.get(cls, set()))):
            desc = f"object-store {tail}()"
        elif tail == "join" and recv and (
                recv[-1] in self.class_threads.get(cls or "", set())
                or recv[-1] in ("_thread", "_finalizer")):
            desc = "Thread.join()"
        if desc is not None:
            fi.blocking.append((held, desc, call.lineno, released))

    # -- transitive closures ----------------------------------------------
    def _close_acquires(self) -> None:
        """acquires*(fn): locks reachable through resolved calls, with the
        site that introduced each (fixpoint; call cycles are fine)."""
        self.acquires_star: dict[str, dict[str, tuple]] = {
            q: {k: (fi.file, ln) for k, ln in fi.acquires.items()}
            for q, fi in self.funcs.items()
        }
        changed = True
        while changed:
            changed = False
            for q, fi in self.funcs.items():
                mine = self.acquires_star[q]
                for callee in fi.callees:
                    for k, site in self.acquires_star.get(callee, {}).items():
                        if k not in mine:
                            mine[k] = site
                            changed = True

    def blocks_star(self) -> dict[str, tuple]:
        """fn -> (op_desc, file, line) for functions containing a blocking
        op anywhere, closed over resolved calls.  Lets the blocking pass
        flag `with L: self.foo()` where foo() parks the thread."""
        out: dict[str, tuple] = {}
        for q, fi in self.funcs.items():
            for _held, desc, line, released in fi.blocking:
                if released is None:   # Condition.wait handled separately
                    out.setdefault(q, (desc, fi.file, line))
                    break
        changed = True
        while changed:
            changed = False
            for q, fi in self.funcs.items():
                if q in out:
                    continue
                for callee in fi.callees:
                    if callee in out:
                        desc, f, ln = out[callee]
                        short = callee.rsplit("::", 1)[-1]
                        out[q] = (f"{short}() -> {desc}", f, ln)
                        changed = True
                        break
        return out


def _queue_nonblocking(call: ast.Call) -> bool:
    """True for Queue.get/put calls that cannot park the caller
    (block=False, or the positional block argument is False)."""
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    for pos in call.args[:2]:   # get(block) / put(item, block)
        if isinstance(pos, ast.Constant) and pos.value is False:
            return True
    return False
