"""`tools.analyze` — unified whole-repo static analysis (ISSUE 7).

One entry point (`python -m tools.analyze`, exit 1 on unsuppressed
findings), one shared AST walk (every file parsed once, one LockModel
shared by the concurrency passes), one findings model, one suppression
syntax:

    # analyze: allow(<rule>) -- <written justification>

Passes (docs/ARCHITECTURE.md "Checked concurrency contracts"):
  lock-order           static ABBA-deadlock cycle detection
  blocking-under-lock  no blocking call inside a `with <lock>` scope
  lane-graph           qos lane submission graph acyclic, no self-waits
  thread-daemon/-shutdown  explicit daemon=, teardown reachability
  qos-seam / resilience-seam / ingest-seam  (migrated from lint_metrics)
  metric-registry      runtime registry hygiene + pinned series

Effect & error-path passes (ISSUE 12, docs/ARCHITECTURE.md "Checked
effect contracts"), built on the shared EffectModel:
  txn-purity           txn/simple_txn closures are rerun-safe
  claim-rollback       registered claim pairs release on every error path
  degrade-not-raise    advisory seams never let exceptions escape
  silent-swallow       data-plane broad excepts count/log/classify
"""

from __future__ import annotations

from .core import (  # noqa: F401  (public API)
    DEFAULT_ROOT,
    REPO,
    Finding,
    Pass,
    Report,
    SourceFile,
    Suppression,
    apply_suppressions,
    load_files,
    run_passes,
)
from .passes import AST_PASSES, RUNTIME_PASSES  # noqa: F401
from .passes import (blocking, claims, degrade, lane_graph, lock_order,
                     metrics, seams, swallow, threads, txn_purity)
from .passes.effects import EffectModel  # noqa: F401
from .passes.locks import LockModel  # noqa: F401


def analyze(root: str = DEFAULT_ROOT, runtime: bool = True,
            files: list[SourceFile] | None = None) -> Report:
    """Run every pass over one shared parse of `root`.

    `runtime=False` skips the registry pass (pure-AST mode: fixture
    trees, unit tests, environments without the package importable).
    """
    if files is None:
        files = load_files(root)
    model = LockModel(files)
    findings: list[Finding] = []
    for sf in files:
        findings.extend(sf.bad_suppressions)
        if sf.parse_error:
            findings.append(Finding(sf.rel, 0, "parse", sf.parse_error))
    findings.extend(lock_order.run(files, model))
    findings.extend(blocking.run(files, model))
    findings.extend(lane_graph.run(files, model))
    findings.extend(threads.run(files))
    findings.extend(seams.run(files))
    effects = EffectModel(files, model)
    findings.extend(txn_purity.run(files, model, effects))
    findings.extend(claims.run(files))
    findings.extend(degrade.run(files))
    findings.extend(swallow.run(files))
    if runtime:
        findings.extend(metrics.run(files))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return apply_suppressions(findings, files)
