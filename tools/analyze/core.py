"""Whole-repo analysis framework core (ISSUE 7 tentpole).

One shared walk: every ``.py`` file under the analysis root is read and
ast-parsed exactly once into a :class:`SourceFile`; every pass runs over
that shared list.  Passes return :class:`Finding` objects (``file:line``,
rule id, message) and never print — rendering, suppression filtering and
exit codes belong to the runner (``python -m tools.analyze``).

Suppression contract (the justification-required syntax):

    self._fut.result()   # analyze: allow(blocking-under-lock) -- <why>

* ``allow(rule)`` names the rule id it silences (comma-separate several).
* The ``-- reason`` is MANDATORY: an allow without one is itself a
  finding (rule ``suppression-syntax``) — unexplained silencing is how
  prose contracts rotted into this PR's motivation.
* A comment alone on a line applies to the next source line; a trailing
  comment applies to its own line.
* A suppression that no longer matches any finding is STALE; the runner
  lists those under ``--stale`` so dead justifications get pruned.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_ROOT = os.path.join(REPO, "juicefs_tpu")

_ALLOW_RE = re.compile(
    r"#\s*analyze:\s*allow\(\s*([A-Za-z0-9_,\- ]*)\s*\)\s*(?:--\s*(\S.*))?"
)


@dataclass(frozen=True)
class Finding:
    """One analysis result, pinned to a source location."""

    file: str       # path relative to the repo root ("" for registry-level)
    line: int       # 1-based; 0 = whole-file / non-source finding
    rule: str       # stable rule id (docs/ARCHITECTURE.md contract table)
    message: str

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else (self.file or "-")
        return f"{loc} {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return {"file": self.file, "line": self.line, "rule": self.rule,
                "message": self.message}


@dataclass
class Suppression:
    """A parsed ``# analyze: allow(...)`` comment."""

    file: str
    comment_line: int   # where the comment physically sits
    target_line: int    # the source line it silences
    rules: tuple[str, ...]
    reason: str
    used: bool = False


class SourceFile:
    """One parsed file: text, split lines, AST, and its suppressions.
    Parsed exactly once; every pass shares this object."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel            # repo-relative, forward slashes
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        self.suppressions: list[Suppression] = []
        self.bad_suppressions: list[Finding] = []
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, comment in self._comments():
            m = _ALLOW_RE.search(comment)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
            reason = (m.group(2) or "").strip()
            # a comment alone on its line silences the NEXT line
            alone = self.lines[i - 1].strip().startswith("#")
            target = i + 1 if alone else i
            if not rules or not reason:
                self.bad_suppressions.append(Finding(
                    self.rel, i, "suppression-syntax",
                    "analyze: allow(...) needs a rule id and a written "
                    "justification: `# analyze: allow(<rule>) -- <reason>`",
                ))
                continue
            self.suppressions.append(
                Suppression(self.rel, i, target, rules, reason))

    def _comments(self):
        """(line, text) for every REAL comment token — the allow-syntax
        regex must never match prose inside a docstring or string
        literal (that is how tools/ documentation kept registering as
        live suppressions)."""
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unparseable tail: fall back to line-scanning what we can
            for i, raw in enumerate(self.lines, start=1):
                stripped = raw.strip()
                if stripped.startswith("#"):
                    yield i, stripped


@dataclass
class Pass:
    """One analysis pass: a name, the rule ids it may emit, and a
    callable over the shared file list."""

    name: str
    rules: tuple[str, ...]
    run: Callable[[list[SourceFile]], list[Finding]]
    doc: str = ""


def load_files(root: str = DEFAULT_ROOT) -> list[SourceFile]:
    """Parse every .py under `root` once (the shared AST walk)."""
    out: list[SourceFile] = []
    root = os.path.abspath(root)
    base = REPO if root.startswith(REPO) else os.path.dirname(root)
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                out.append(SourceFile(path, rel, f.read()))
    return out


@dataclass
class Report:
    """Everything one analysis run produced, pre-rendering."""

    findings: list[Finding] = field(default_factory=list)   # unsuppressed
    suppressed: list[tuple[Finding, Suppression]] = field(default_factory=list)
    stale: list[Suppression] = field(default_factory=list)

    @property
    def failed(self) -> bool:
        return bool(self.findings)


def apply_suppressions(findings: Iterable[Finding],
                       files: list[SourceFile]) -> Report:
    """Split findings into unsuppressed vs suppressed, marking which
    allow-comments earned their keep (the rest are stale)."""
    by_file: dict[str, list[Suppression]] = {}
    for sf in files:
        by_file.setdefault(sf.rel, []).extend(sf.suppressions)
    report = Report()
    for f in findings:
        sup = None
        for s in by_file.get(f.file, ()):
            if f.rule in s.rules and f.line == s.target_line:
                sup = s
                break
        if sup is None:
            report.findings.append(f)
        else:
            sup.used = True
            report.suppressed.append((f, sup))
    for sf in files:
        report.stale.extend(s for s in sf.suppressions if not s.used)
    return report


def run_passes(files: list[SourceFile], passes: Iterable[Pass]) -> Report:
    """Run passes over the pre-parsed files and fold in the framework's
    own findings (malformed suppressions, unparseable files)."""
    findings: list[Finding] = []
    for sf in files:
        findings.extend(sf.bad_suppressions)
        if sf.parse_error:
            findings.append(Finding(sf.rel, 0, "parse", sf.parse_error))
    for p in passes:
        findings.extend(p.run(files))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return apply_suppressions(findings, files)


# ---------------------------------------------------------------------------
# shared AST helpers (used by several passes; lived as copy-pasted walkers
# in tools/lint_metrics.py before ISSUE 7)

def call_name(node: ast.Call) -> Optional[str]:
    """Bare callee name: `Foo(...)` and `pkg.mod.Foo(...)` both -> "Foo"."""
    return getattr(node.func, "id", None) or getattr(node.func, "attr", None)


def attr_chain(node: ast.AST) -> Optional[list[str]]:
    """`self.store._pool` -> ["self", "store", "_pool"]; None when the
    expression is not a pure name/attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def parent_map(tree: ast.AST) -> dict[int, ast.AST]:
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents
